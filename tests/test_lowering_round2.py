"""Differential tests for the second batch of round-2 lowering
coverage: filters applied directly after a variable head
(`%var[ ... ]`, scopes.py:390-408 ValueScope-wraps each resolved value
so maps AND scalars self-filter while lists iterate), key interpolation
through rule-body (root-basis) `let`s, interpolation inside value
scopes, and `count()` function variables compared against numeric
literals. Every case must lower (no host fallback) and match the CPU
oracle bit-for-bit."""

import pathlib

import pytest

from guard_tpu.core.parser import parse_rules_file
from guard_tpu.core.scopes import RootScope
from guard_tpu.core.evaluator import eval_rules_file
from guard_tpu.core.values import from_plain
from guard_tpu.ops.encoder import Interner, encode_batch
from guard_tpu.ops.ir import compile_rules_file
from guard_tpu.ops.kernels import BatchEvaluator

STATUS = {0: "PASS", 1: "FAIL", 2: "SKIP"}


def _oracle(rf, doc):
    from guard_tpu.commands.report import rule_statuses_from_root

    scope = RootScope(rf, doc)
    eval_rules_file(rf, scope, None)
    root = scope.reset_recorder().extract()
    return {n: s.value for n, s in rule_statuses_from_root(root).items()}


def _differential(rules_text, docs_plain, expect_host=0, allow_unsure=False):
    from guard_tpu.ops.fnvars import precompute_fn_values

    rf = parse_rules_file(rules_text, "cov2.guard")
    docs = [from_plain(d) for d in docs_plain]
    fn_vars, fn_vals, fn_err = precompute_fn_values(rf, docs)
    assert not fn_err, "unexpected function errors in differential docs"
    batch, interner = encode_batch(
        docs, fn_values=fn_vals, fn_var_order=fn_vars
    )
    compiled = compile_rules_file(rf, interner)
    assert len(compiled.host_rules) == expect_host, [
        r.rule_name for r in compiled.host_rules
    ]
    if not compiled.rules:
        return
    evaluator = BatchEvaluator(compiled)
    statuses = evaluator(batch)
    unsure = evaluator.last_unsure
    for di, doc in enumerate(docs):
        oracle = _oracle(rf, doc)
        # device statuses merged by name exactly like the report layer
        # (report.rule_statuses_from_root): non-SKIP beats SKIP, FAIL
        # dominates — for unique names this is the identity
        merged = {}
        skip_names = set()
        for ri, crule in enumerate(compiled.rules):
            if unsure is not None and bool(unsure[di, ri]):
                assert allow_unsure, "unexpected unsure flag"
                skip_names.add(crule.name)
                continue
            dev = STATUS[int(statuses[di, ri])]
            prev = merged.get(crule.name)
            if prev is None or (prev == "SKIP" and dev != "SKIP"):
                merged[crule.name] = dev
            elif dev == "FAIL":
                merged[crule.name] = "FAIL"
        for name, dev in merged.items():
            if name in skip_names:
                continue
            assert dev == oracle[name], (
                f"doc {di} ({docs_plain[di]}) rule {name}: "
                f"device={dev} oracle={oracle[name]}"
            )


# ---------------------------------------------------------------------------
# filter after a variable head: `%var[ ... ]`
# ---------------------------------------------------------------------------
def test_filter_after_var_maps_self_filter():
    # each var value (a map) filters ITSELF — not its children
    _differential(
        """
let tasks = Resources.*[ Type == 'Task' ]
let shared = %tasks[ Properties.Arn is_string ]

rule shared_tagged when %shared !empty {
    %shared.Metadata.Shared exists
}
""",
        [
            # one task matches the inner filter and has Metadata.Shared
            {
                "Resources": {
                    "a": {
                        "Type": "Task",
                        "Properties": {"Arn": "arn:x"},
                        "Metadata": {"Shared": True},
                    },
                    "b": {"Type": "Task", "Properties": {"Arn": {"Ref": "r"}}},
                }
            },
            # matches the filter but lacks Metadata -> FAIL
            {
                "Resources": {
                    "a": {"Type": "Task", "Properties": {"Arn": "arn:x"}}
                }
            },
            # no task passes the filter -> when gate SKIPs
            {
                "Resources": {
                    "a": {"Type": "Task", "Properties": {"Arn": {"Ref": "r"}}}
                }
            },
            # no tasks at all -> SKIP
            {"Resources": {"x": {"Type": "Other"}}},
        ],
    )


def test_filter_after_var_trailing_parts_and_nested_filters():
    _differential(
        """
let buckets = Resources.*[ Type == 'Bucket' ]

rule prod_encrypted when %buckets[ Props.Env == 'prod' ] !empty {
    %buckets[ Props.Env == 'prod' ].Props.Enc == true
}
""",
        [
            {
                "Resources": {
                    "p": {"Type": "Bucket", "Props": {"Env": "prod", "Enc": True}},
                    "d": {"Type": "Bucket", "Props": {"Env": "dev", "Enc": False}},
                }
            },
            {
                "Resources": {
                    "p": {"Type": "Bucket", "Props": {"Env": "prod", "Enc": False}}
                }
            },
            {
                "Resources": {
                    "d": {"Type": "Bucket", "Props": {"Env": "dev", "Enc": True}}
                }
            },
        ],
    )


def test_filter_after_var_list_values_iterate():
    # var values that are LISTS iterate their elements through the
    # filter (scopes.py:727-747), each element in its own scope
    _differential(
        """
let perms = Resources.*.Ingress

rule only_https when %perms !empty {
    %perms[ Port == 443 ].Cidr == '0.0.0.0/0'
}
""",
        [
            {
                "Resources": {
                    "sg": {
                        "Ingress": [
                            {"Port": 443, "Cidr": "0.0.0.0/0"},
                            {"Port": 22, "Cidr": "10.0.0.0/8"},
                        ]
                    }
                }
            },
            {
                "Resources": {
                    "sg": {"Ingress": [{"Port": 443, "Cidr": "10.1.0.0/16"}]}
                }
            },
            # filter selects nothing -> clause SKIPs inside the rule
            {"Resources": {"sg": {"Ingress": [{"Port": 22, "Cidr": "x"}]}}},
        ],
    )


def test_filter_after_var_scalar_values_self_filter():
    # scalar var values evaluate the filter on THEMSELVES
    # (scopes.py:749-757) instead of UnResolving like `.*[...]` scalars
    _differential(
        """
let names = Resources.*.Name

rule has_prod when %names[ this == 'prod' ] !empty {
    Resources exists
}
""",
        [
            {"Resources": {"a": {"Name": "prod"}, "b": {"Name": "dev"}}},
            {"Resources": {"a": {"Name": "dev"}}},
        ],
    )


def test_explicit_star_after_var_equals_implicit():
    # `%var[*][f]` hits the same skip as the implicit form
    # (scopes.py:399-400): identical statuses
    _differential(
        """
let tasks = Resources.*[ Type == 'T' ]

rule r when %tasks !empty { %tasks[*][ P exists ].P == 1 }
""",
        [
            {"Resources": {"a": {"Type": "T", "P": 1}}},
            {"Resources": {"a": {"Type": "T", "P": 2}, "b": {"Type": "T"}}},
        ],
    )


# ---------------------------------------------------------------------------
# key interpolation: rule-body lets and value scopes
# ---------------------------------------------------------------------------
def test_interpolation_rule_body_let():
    # `let refs = some ...` bound INSIDE the rule body resolves from
    # the document root (BlockScope root), so it lowers like file lets
    _differential(
        """
rule subnets_are_subnets when Resources exists {
    let refs = some Resources.*[ Type == 'Assoc' ].SubnetId.Ref
    Resources.%refs.Type == 'Subnet'
}
""",
        [
            {
                "Resources": {
                    "s1": {"Type": "Subnet"},
                    "a1": {"Type": "Assoc", "SubnetId": {"Ref": "s1"}},
                }
            },
            {
                "Resources": {
                    "s1": {"Type": "Gateway"},
                    "a1": {"Type": "Assoc", "SubnetId": {"Ref": "s1"}},
                }
            },
            {"Resources": {"x": {"Type": "Other"}}},
        ],
    )


def test_interpolation_inside_value_scope():
    # a root-bound query variable interpolated INSIDE a filter: the
    # variable still resolves from the root basis
    _differential(
        """
let keys = some Settings.Required[*]

rule all_have_required when Resources exists {
    Resources.*[ Type == 'T' ].Props.%keys exists
}
""",
        [
            {
                "Settings": {"Required": ["Enc", "Ver"]},
                "Resources": {
                    "a": {"Type": "T", "Props": {"Enc": 1, "Ver": 2}}
                },
            },
            {
                "Settings": {"Required": ["Enc", "Ver"]},
                "Resources": {"a": {"Type": "T", "Props": {"Enc": 1}}},
            },
        ],
    )


# ---------------------------------------------------------------------------
# count() function variables
# ---------------------------------------------------------------------------
COUNT_DOCS = [
    {"Resources": {"a": {"P": {"Name": "x"}}, "b": {"P": {"Name": "y"}}}},
    {"Resources": {"a": {"P": {"Name": "x"}}}},
    {"Resources": {"a": {"P": {}}, "b": {"P": {"Name": "y"}}}},
    {"Other": 1},
]


def test_count_eq_and_ordering():
    _differential(
        """
let names = Resources.*.P.Name
let n = count(%names)

rule has_two when %n == 2 { Resources exists }
rule has_not_two when %n != 2 { Resources exists }
rule more_than_one when %n > 1 { Resources exists }
rule at_most_one when %n <= 1 { Resources exists }
""",
        COUNT_DOCS,
    )


def test_count_in_list_and_range():
    _differential(
        """
let names = Resources.*.P.Name
let n = count(%names)

rule one_or_two when %n in [1, 2] { Resources exists }
rule not_one_or_two when %n not in [1, 2] { Resources exists }
rule in_range when %n in r[1, 2] { Resources exists }
rule eq_range when %n == r(0, 2] { Resources exists }
rule ne_range when %n != r(0, 2] { Resources exists }
""",
        COUNT_DOCS,
    )


def test_count_not_comparable_kinds():
    # INT vs float/string: NotComparable -> FAIL surviving `not`
    _differential(
        """
let n = count(Resources.*)

rule f1 when %n == 2.0 { Resources exists }
rule f2 when %n != 2.0 { Resources exists }
rule f3 when %n > 'a' { Resources exists }
rule f4 when %n in [1.5, 'x'] { Resources exists }
""",
        COUNT_DOCS,
    )


def test_count_unary_ops():
    _differential(
        """
let n = count(Resources.*)

rule e1 when %n exists { Resources exists }
rule e2 when %n !exists { Resources exists }
rule e3 when %n empty { Resources exists }
rule e4 when %n !empty { Resources exists }
rule e5 when %n is_int { Resources exists }
rule e6 when %n is_string { Resources exists }
""",
        COUNT_DOCS,
    )


def test_count_in_rule_body_and_literal_rhs_var():
    _differential(
        """
let want = 2

rule body_count when Resources exists {
    let n = count(Resources.*.P.Name)
    %n == %want
}
""",
        COUNT_DOCS,
    )


def test_count_of_filtered_query():
    _differential(
        """
let n = count(Resources.*[ Type == 'T' ])

rule two_ts when %n >= 2 { Resources exists }
""",
        [
            {"Resources": {"a": {"Type": "T"}, "b": {"Type": "T"}}},
            {"Resources": {"a": {"Type": "T"}, "b": {"Type": "U"}}},
            {"Other": 1},
        ],
    )


# ---------------------------------------------------------------------------
# previously-host reference examples now lower end to end
# ---------------------------------------------------------------------------
REF_EX = pathlib.Path("/root/reference/guard-examples")


@pytest.mark.parametrize(
    "name",
    ["ecs-taskdef.guard", "dynamodb-table-sse.guard",
     "redshift-clustersubnetgroup.guard"],
)
def test_reference_examples_fully_lower(name):
    matches = list(REF_EX.rglob(name))
    if not matches:
        pytest.skip("reference examples unavailable")
    rf = parse_rules_file(matches[0].read_text(), name)
    compiled = compile_rules_file(rf, Interner())
    assert not compiled.host_rules, [r.rule_name for r in compiled.host_rules]


def test_corpus_count_files_fully_lower():
    corpus = pathlib.Path(__file__).resolve().parent.parent / "corpus" / "rules"
    files = sorted(corpus.glob("functions_count*.guard"))
    assert files, "corpus count files missing"
    for f in files:
        rf = parse_rules_file(f.read_text(), f.name)
        compiled = compile_rules_file(rf, Interner())
        assert not compiled.host_rules, (
            f.name,
            [r.rule_name for r in compiled.host_rules],
        )


def test_redshift_example_differential():
    """The redshift example end to end on synthetic docs (its rule
    chains two levels of Ref-indirection through rule-body lets)."""
    matches = list(REF_EX.rglob("redshift-clustersubnetgroup.guard"))
    if not matches:
        pytest.skip("reference examples unavailable")
    rules = matches[0].read_text()
    docs = [
        {
            "Resources": {
                "subnet": {"Type": "AWS::EC2::Subnet"},
                "grp": {
                    "Type": "AWS::Redshift::ClusterSubnetGroup",
                    "Properties": {"SubnetIds": [{"Ref": "subnet"}]},
                },
                "assoc": {
                    "Type": "AWS::EC2::SubnetRouteTableAssociation",
                    "Properties": {
                        "SubnetId": {"Ref": "subnet"},
                        "RouteTableId": {"Ref": "rt"},
                    },
                },
                "rt": {"Type": "AWS::EC2::RouteTable"},
                "route": {
                    "Type": "AWS::EC2::Route",
                    "Properties": {
                        "GatewayId": {"Ref": "gw"},
                        "RouteTableId": {"Ref": "rt"},
                    },
                },
                "gw": {"Type": "AWS::EC2::InternetGateway"},
            }
        },
        {
            "Resources": {
                "subnet": {"Type": "AWS::EC2::Subnet"},
                "grp": {
                    "Type": "AWS::Redshift::ClusterSubnetGroup",
                    "Properties": {"SubnetIds": [{"Ref": "subnet"}]},
                },
                "assoc": {
                    "Type": "AWS::EC2::SubnetRouteTableAssociation",
                    "Properties": {
                        "SubnetId": {"Ref": "subnet"},
                        "RouteTableId": {"Ref": "rt"},
                    },
                },
                "rt": {"Type": "AWS::EC2::RouteTable"},
                "route": {
                    "Type": "AWS::EC2::Route",
                    "Properties": {
                        "GatewayId": {"Ref": "gw"},
                        "RouteTableId": {"Ref": "rt"},
                    },
                },
                "gw": {"Type": "AWS::EC2::VPNGateway"},
            }
        },
        {"Resources": {"x": {"Type": "Other"}}},
    ]
    _differential(rules, docs)


# ---------------------------------------------------------------------------
# duplicate rule names (first-non-SKIP named-ref semantics)
# ---------------------------------------------------------------------------
def test_duplicate_rule_names_lower():
    _differential(
        """
rule checks when Resources.A exists { Resources.A == 1 }
rule checks when Resources.B exists { Resources.B == 2 }

rule uses when checks { Resources exists }
rule negates when !checks { Resources exists }
""",
        [
            {"Resources": {"A": 1}},           # first PASS
            {"Resources": {"A": 9}},           # first FAIL
            {"Resources": {"B": 2}},           # first SKIP, second PASS
            {"Resources": {"B": 9}},           # first SKIP, second FAIL
            {"Resources": {"C": 0}},           # both SKIP
            {"Resources": {"A": 1, "B": 9}},   # PASS then FAIL -> first wins
        ],
    )


# ---------------------------------------------------------------------------
# root-bound query RHS combinations
# ---------------------------------------------------------------------------
def test_eq_against_root_bound_query_rhs():
    # per-origin LHS == one shared root-resolved RHS set
    _differential(
        """
let allowed = Settings.Allowed[*]

rule zones_match when Resources exists {
    Resources.*[ Type == 'T' ].Zones.* == %allowed
}
""",
        [
            {
                "Settings": {"Allowed": ["a", "b"]},
                "Resources": {"x": {"Type": "T", "Zones": {"z1": "a", "z2": "b"}}},
            },
            {
                "Settings": {"Allowed": ["a", "b"]},
                "Resources": {"x": {"Type": "T", "Zones": {"z1": "a"}}},
            },
            {
                "Settings": {"Allowed": ["a"]},
                "Resources": {"x": {"Type": "T", "Zones": {"z1": "a", "z2": "c"}}},
            },
            {"Settings": {"Allowed": ["a"]}, "Resources": {"x": {"Type": "U"}}},
        ],
    )


def test_ne_against_root_bound_query_rhs():
    _differential(
        """
let banned = Settings.Banned[*]

rule no_banned when Resources exists {
    Resources.*[ Type == 'T' ].Zones.* != %banned
}
""",
        [
            {
                "Settings": {"Banned": ["x"]},
                "Resources": {"r": {"Type": "T", "Zones": {"z": "a"}}},
            },
            {
                "Settings": {"Banned": ["a"]},
                "Resources": {"r": {"Type": "T", "Zones": {"z": "a"}}},
            },
            {
                "Settings": {"Banned": ["a", "b"]},
                "Resources": {"r": {"Type": "T", "Zones": {"z1": "a", "z2": "c"}}},
            },
        ],
    )


def test_both_sides_root_bound_inside_filter():
    # `%a IN %b` (and ==) inside a value scope with both vars root-bound:
    # the clause broadcasts from the root
    _differential(
        """
let open_ports = Resources.*.Open[*]
let allowed_ports = Settings.Allowed[*]

rule gated when Resources exists {
    Resources.*[ Type == 'SG' ].Props {
        %open_ports IN %allowed_ports
        Level exists
    }
}

rule gated_eq when Resources exists {
    Resources.*[ Type == 'SG' ].Props {
        %open_ports == %allowed_ports
    }
}
""",
        [
            {
                "Settings": {"Allowed": [80, 443]},
                "Resources": {
                    "sg": {"Type": "SG", "Open": [80], "Props": {"Level": 1}}
                },
            },
            {
                "Settings": {"Allowed": [80, 443]},
                "Resources": {
                    "sg": {"Type": "SG", "Open": [22], "Props": {"Level": 1}}
                },
            },
            {
                "Settings": {"Allowed": [80]},
                "Resources": {
                    "sg": {"Type": "SG", "Open": [80], "Props": {"Level": 1}}
                },
            },
        ],
    )


# ---------------------------------------------------------------------------
# ordering comparisons against query RHS (CommonOperator cartesian)
# ---------------------------------------------------------------------------
def test_ordering_query_rhs_numbers():
    _differential(
        """
rule caps when Resources exists {
    Resources.*.Used < Resources.*.Limit
}
rule caps_some when Resources exists {
    some Resources.*.Used >= Resources.*.Limit
}
""",
        [
            {"Resources": {"a": {"Used": 1, "Limit": 10}, "b": {"Used": 2, "Limit": 8}}},
            {"Resources": {"a": {"Used": 9, "Limit": 5}}},
            {"Resources": {"a": {"Used": 1}}},           # rhs unresolved
            {"Resources": {"a": {"Limit": 5}}},          # lhs unresolved
        ],
    )


def test_ordering_query_rhs_strings_and_mixed():
    _differential(
        """
rule names_ordered when Resources exists {
    Resources.*.First < Resources.*.Second
}
""",
        [
            {"Resources": {"a": {"First": "alpha", "Second": "beta"}}},
            {"Resources": {"a": {"First": "zeta", "Second": "beta"}}},
            # mixed kinds: NotComparable pairs FAIL
            {"Resources": {"a": {"First": "alpha", "Second": 3}}},
            {"Resources": {"a": {"First": 1, "Second": 2}}},
        ],
    )


def test_ordering_query_rhs_list_flatten():
    _differential(
        """
rule all_below when Resources exists {
    Resources.*.Vals < Resources.*.Cap
}
""",
        [
            {"Resources": {"a": {"Vals": [1, 2, 3], "Cap": 10}}},
            {"Resources": {"a": {"Vals": [1, 20], "Cap": 10}}},
        ],
    )


def test_parse_epoch_fixture_shape():
    """The reference's parse_epoch.guard: fn-var < fn-var ordering."""
    _differential(
        """
let asg = Resources.*[ Type == 'ASG' ]
let updated_at = parse_epoch(%asg.UpdatedAt)
let limit = parse_epoch("3023-05-24T15:22:56.123Z")

rule CHECK_UPDATED_AT when %asg !empty {
  %limit < %updated_at
}
""",
        [
            {"Resources": {"a": {"Type": "ASG", "UpdatedAt": "2024-01-01T00:00:00Z"}}},
            {"Resources": {"a": {"Type": "ASG", "UpdatedAt": "3024-01-01T00:00:00Z"}}},
            {"Resources": {"a": {"Type": "Other"}}},
        ],
    )


def test_ordering_root_bound_rhs_inside_filter():
    _differential(
        """
let cap = Settings.Cap

rule under_cap when Resources exists {
    Resources.*[ Type == 'T' ].Size < %cap
}
""",
        [
            {"Settings": {"Cap": 10}, "Resources": {"a": {"Type": "T", "Size": 5}}},
            {"Settings": {"Cap": 10}, "Resources": {"a": {"Type": "T", "Size": 15}}},
        ],
    )


# ---------------------------------------------------------------------------
# indexed variable key interpolation: `.%names[k]`
# ---------------------------------------------------------------------------
def test_indexed_interpolation():
    # the reference picks the k-th variable ENTRY and then ALSO walks
    # the [k] part into the resolved value (eval_context.rs:421-526)
    _differential(
        """
let names = Names[*]

rule first_val when Names exists { Resources.%names[0] == 10 }
rule second when Names exists { Resources.%names[1] exists }
rule oob when Names exists { Resources.%names[9] exists }
""",
        [
            {
                "Names": ["alpha", "beta"],
                "Resources": {"alpha": [10, 20], "beta": {"x": 1}},
            },
            {
                "Names": ["beta", "alpha"],
                "Resources": {"alpha": [10, 20], "beta": [7, 8]},
            },
            {"Names": ["missing"], "Resources": {"alpha": [10]}},
        ],
    )


def test_indexed_interpolation_literal_var():
    _differential(
        """
let names = ['alpha', 'beta']

rule zero when Resources exists { Resources.%names[0] exists }
rule one_oob when Resources exists { Resources.%names[1] exists }
""",
        [
            {"Resources": {"alpha": [1], "beta": [2]}},
            {"Resources": {"gamma": 1}},
        ],
    )
