"""Bit-parity suite for the vectorized results plane (PR 2).

`validate --backend tpu` and `sweep` output under the vectorized rim
(GUARD_TPU_VECTOR_RIM=1, the default) must be byte-identical to the
scalar per-(doc, rule) walk (GUARD_TPU_VECTOR_RIM=0) over mixed
corpora: fail-heavy docs, unsure-flagged docs (variable key
interpolation over non-strings), host-fallback rules (now()), fn-var
files (per-file re-encoded batches), packed and per-file dispatch —
asserting identical console output, structured reports, exit codes and
JUnit XML. Plus unit coverage for the rim reduction lattice and the
pass-A mask plane."""

import json

import numpy as np
import pytest

from guard_tpu.cli import run
from guard_tpu.utils.io import Reader, Writer

# fail-heavy device-lowerable rules (same-name rules merge; `sse`
# fails on unencrypted buckets)
RULES_MAIN = (
    "let b = Resources.*[ Type == 'AWS::S3::Bucket' ]\n"
    "rule sse when %b !empty { %b.Properties.Enc == true }\n"
    "rule named { Resources.* { Type exists } }\n"
)

# now() is a documented host-only construct: the whole file falls back
# to the CPU oracle (ir.HOST_ONLY_CONSTRUCTS)
RULES_HOST = (
    "let t = now()\n"
    "rule fresh { Resources exists }\n"
)

# variable key interpolation: non-string values in %names flag the doc
# unsure (kernels.StepKeyInterpVar), routing it to the oracle
RULES_UNSURE = (
    "let names = Selection.targets\n"
    "rule sel { Resources.%names exists }\n"
)

# precomputable function let: the file re-encodes its batch per file
# (ops/fnvars.py) and is excluded from packing by ir.pack_compatible
RULES_FN = (
    "let up = to_upper(Meta.name)\n"
    "rule upper when Meta.name exists { %up == 'WIDGET' }\n"
)


def _mk_corpus(tmp_path, with_extra_rules=True):
    rdir = tmp_path / "rules"
    rdir.mkdir(exist_ok=True)
    (rdir / "main.guard").write_text(RULES_MAIN)
    if with_extra_rules:
        (rdir / "host.guard").write_text(RULES_HOST)
        (rdir / "unsure.guard").write_text(RULES_UNSURE)
        (rdir / "fnvar.guard").write_text(RULES_FN)
    data = tmp_path / "data"
    data.mkdir(exist_ok=True)
    for i in range(10):
        doc = {
            "Resources": {
                "b": {
                    "Type": "AWS::S3::Bucket",
                    # docs 0, 3, 6, 9 fail `sse`
                    "Properties": {"Enc": (i % 3) != 0},
                }
            },
            "Meta": {"name": "widget" if i % 2 else "gadget"},
            # docs 0, 4, 8 carry a non-string selection target: the
            # unsure flag routes them to the oracle
            "Selection": {"targets": [3] if i % 4 == 0 else ["b"]},
        }
        (data / f"t{i:03d}.json").write_text(json.dumps(doc))
    return rdir, data


def _validate(rule_args, data, extra=()):
    w = Writer.buffered()
    rc = run(
        ["validate", *rule_args, "-d", str(data), "--backend", "tpu",
         *extra],
        writer=w,
        reader=Reader(),
    )
    return rc, w.out.getvalue(), w.err.getvalue()


def _both(monkeypatch, fn):
    monkeypatch.setenv("GUARD_TPU_VECTOR_RIM", "1")
    vec = fn()
    monkeypatch.setenv("GUARD_TPU_VECTOR_RIM", "0")
    scalar = fn()
    return vec, scalar


MODES = [
    [],
    ["--show-summary", "all"],
    ["--statuses-only"],
    ["-o", "yaml"],
    ["--structured", "-o", "json", "--show-summary", "none"],
    ["--structured", "-o", "junit", "--show-summary", "none"],
]


@pytest.mark.parametrize("mode", MODES, ids=lambda m: "_".join(m) or "default")
def test_validate_parity_mixed_corpus(tmp_path, monkeypatch, mode):
    """Mixed rules (fail-heavy + host-fallback + unsure + fn-var) over
    a mixed corpus: every output mode byte-identical across the rim
    paths, including JUnit and structured reports."""
    rdir, data = _mk_corpus(tmp_path)
    rule_args = ["-r", *(str(rf) for rf in sorted(rdir.glob("*.guard")))]
    vec, scalar = _both(
        monkeypatch, lambda: _validate(rule_args, data, mode)
    )
    assert vec == scalar


@pytest.mark.parametrize("pack", ["1", "0"], ids=["packed", "perfile"])
def test_validate_parity_pack_and_perfile(tmp_path, monkeypatch, pack):
    """Parity holds on both dispatch paths: packed executables (the
    device-side rim reductions) and per-file dispatch (host-side
    rim_reduce fallback)."""
    rdir, data = _mk_corpus(tmp_path)
    monkeypatch.setenv("GUARD_TPU_PACK", pack)
    rule_args = ["-r", *(str(rf) for rf in sorted(rdir.glob("*.guard")))]
    vec, scalar = _both(monkeypatch, lambda: _validate(rule_args, data))
    assert vec == scalar
    assert vec[0] != 0  # the corpus contains genuine failures


def test_sweep_parity(tmp_path, monkeypatch):
    """Sweep chunk tallies (counts, failed list, exit code) identical
    across the rim paths — including the dict-overwrite semantics for
    same-name rules across files and oracle-touched docs."""
    rdir, data = _mk_corpus(tmp_path)

    def go(tag):
        w = Writer.buffered()
        rule_args = ["-r", *(str(rf) for rf in sorted(rdir.glob("*.guard")))]
        rc = run(
            ["sweep", *rule_args, "-d", str(data),
             "--manifest", str(tmp_path / f"m{tag}.jsonl"),
             "--chunk-size", "4"],
            writer=w,
            reader=Reader(),
        )
        summary = json.loads(w.out.getvalue().strip().splitlines()[-1])
        summary.pop("manifest")
        return rc, summary, w.err.getvalue()

    monkeypatch.setenv("GUARD_TPU_VECTOR_RIM", "1")
    vec = go("vec")
    monkeypatch.setenv("GUARD_TPU_VECTOR_RIM", "0")
    scalar = go("sca")
    assert vec == scalar


def test_all_pass_corpus_settles_in_array(tmp_path, monkeypatch):
    """The rim counters: an all-PASS corpus under the vectorized rim
    materializes ZERO per-rule dicts — every doc settles through the
    per-unique-status-row cache — while the scalar rim materializes
    every one."""
    from guard_tpu.ops import backend

    rdir = tmp_path / "rules"
    rdir.mkdir()
    (rdir / "a.guard").write_text("rule a { Resources exists }\n")
    (rdir / "b.guard").write_text("rule b { Resources.*.Type exists }\n")
    data = tmp_path / "data"
    data.mkdir()
    for i in range(6):
        (data / f"t{i}.json").write_text(
            json.dumps({"Resources": {"x": {"Type": "T"}}})
        )

    monkeypatch.setenv("GUARD_TPU_VECTOR_RIM", "1")
    backend.reset_rim_stats()
    rc, out, _ = _validate(
        ["-r", str(rdir / "a.guard"), str(rdir / "b.guard")], data
    )
    assert rc == 0
    stats = backend.rim_stats()
    assert stats["docs_materialized"] == 0
    assert stats["docs_settled"] == 12  # 6 docs x 2 rule files

    monkeypatch.setenv("GUARD_TPU_VECTOR_RIM", "0")
    backend.reset_rim_stats()
    rc2, out2, _ = _validate(
        ["-r", str(rdir / "a.guard"), str(rdir / "b.guard")], data
    )
    assert (rc2, out2) == (rc, out)
    stats = backend.rim_stats()
    assert stats["docs_materialized"] == 12
    assert stats["docs_settled"] == 0


def test_rim_reduce_lattice():
    """The numpy rim reduction implements the report layer's status
    lattice exactly: FAIL dominates, PASS beats SKIP, SKIP identity —
    per name group and per file — plus the any-fail/any-unsure bitmaps
    and the last-rule-wins block."""
    from guard_tpu.ops.ir import FAIL, PASS, SKIP
    from guard_tpu.ops.kernels import rim_reduce

    # two files: file 0 has rules [a, a, b], file 1 has [c]
    statuses = np.array(
        [
            [PASS, SKIP, SKIP, PASS],   # a: PASS (non-SKIP beats SKIP)
            [SKIP, FAIL, PASS, SKIP],   # a: FAIL (FAIL dominates)
            [SKIP, SKIP, SKIP, FAIL],
        ],
        np.int8,
    )
    unsure = np.zeros((3, 4), bool)
    unsure[2, 1] = True
    group_ids = np.array([0, 0, 1, 2], np.int32)
    file_ids = np.array([0, 0, 0, 1], np.int32)
    last_ids = np.array([1, 2, 3], np.int32)
    name_st, name_un, doc_st, any_fail, any_un, name_last = rim_reduce(
        statuses, unsure, group_ids, file_ids, last_ids, 3, 2
    )
    assert name_st.tolist() == [
        [PASS, SKIP, PASS], [FAIL, PASS, SKIP], [SKIP, SKIP, FAIL]
    ]
    assert name_un.tolist() == [
        [False, False, False], [False, False, False], [True, False, False]
    ]
    assert doc_st.tolist() == [[PASS, PASS], [FAIL, SKIP], [SKIP, FAIL]]
    assert any_fail.tolist() == [
        [False, False], [True, False], [False, True]
    ]
    assert any_un.tolist() == [
        [False, False], [False, False], [True, False]
    ]
    # last-rule-wins (the sweep's dict-overwrite semantics): group 0's
    # last rule is index 1
    assert name_last[:, 0].tolist() == [SKIP, FAIL, SKIP]


def test_rim_masks_plane():
    """Pass-A mask arithmetic: need_oracle / needs_statuses /
    materialize reproduce the scalar conditionals."""
    from guard_tpu.ops.backend import rim_masks

    any_fail = np.array([True, False, False, False])
    any_un = np.array([False, True, False, False])
    host = np.array([False, False, True, False])
    no, ns, mat = rim_masks(
        any_fail, any_un, host, has_host_rules=False, rich_mode=False,
        statuses_only=False,
    )
    assert no.tolist() == [True, True, True, False]
    assert ns.tolist() == [False, True, True, False]
    assert mat.tolist() == [True, True, True, False]
    # statuses-only: FAIL alone no longer needs the oracle, but its
    # report still lists failing names -> it must materialize
    no, ns, mat = rim_masks(
        any_fail, any_un, host, has_host_rules=False, rich_mode=False,
        statuses_only=True,
    )
    assert no.tolist() == [False, True, True, False]
    assert mat.tolist() == [True, True, True, False]
    # host rules / rich output force everything
    no, ns, mat = rim_masks(
        any_fail, any_un, host, has_host_rules=True, rich_mode=False,
        statuses_only=False,
    )
    assert bool(np.all(no)) and bool(np.all(ns)) and bool(np.all(mat))
    no, ns, mat = rim_masks(
        any_fail, any_un, host, has_host_rules=False, rich_mode=True,
        statuses_only=False,
    )
    assert bool(np.all(no)) and bool(np.all(mat))
    # show-summary pass/skip rows materialize everything without
    # touching the oracle masks
    no, ns, mat = rim_masks(
        any_fail, any_un, host, has_host_rules=False, rich_mode=False,
        statuses_only=False, show_rich=True,
    )
    assert no.tolist() == [True, True, True, False]
    assert bool(np.all(mat))


def test_device_rim_blocks_match_host(tmp_path):
    """The device-side rim reduction (mesh._rim_device behind the
    packed dispatch) returns the same blocks as a host rim_reduce over
    the collected status matrix."""
    from guard_tpu.core.parser import parse_rules_file
    from guard_tpu.core.values import from_plain
    from guard_tpu.ops.backend import _evaluate_packs
    from guard_tpu.ops.encoder import encode_batch
    from guard_tpu.ops.ir import (
        build_rim_spec,
        compile_rules_file,
        pack_compatible,
    )
    from guard_tpu.ops.kernels import rim_reduce

    docs = [
        from_plain({"Resources": {"b": {"Type": "AWS::S3::Bucket",
                                        "Properties": {"Enc": i % 2 == 0}}}})
        for i in range(5)
    ]
    rfs = [
        parse_rules_file(RULES_MAIN, "main.guard"),
        parse_rules_file("rule t { Resources.b.Type == /S3/ }\n", "t.guard"),
    ]
    batch, interner = encode_batch(docs)
    items = [
        (fi, compile_rules_file(rf, interner)) for fi, rf in enumerate(rfs)
    ]
    items = [(fi, c) for fi, c in items if pack_compatible(c) is None]
    assert len(items) == 2
    results = _evaluate_packs(items, batch)
    for fi, c in items:
        st, un, _hd, rim = results[fi]
        assert rim is not None
        spec = build_rim_spec([c.rules])
        host = rim_reduce(
            st, un, spec.group_ids, spec.file_ids, spec.last_ids,
            spec.n_groups, spec.n_files,
        )
        np.testing.assert_array_equal(rim[0], host[0])
        np.testing.assert_array_equal(rim[1], host[1])
        np.testing.assert_array_equal(rim[2], host[2][:, 0])
        np.testing.assert_array_equal(rim[3], host[3][:, 0])
        np.testing.assert_array_equal(rim[4], host[4][:, 0])
        np.testing.assert_array_equal(rim[5], host[5])
        assert rim[6] == spec.file_group_names[0]
