"""Value model comparison semantics, pinned to path_value.rs behavior."""

import pytest

from guard_tpu.core.errors import NotComparableError
from guard_tpu.core.values import (
    LOWER_INCLUSIVE,
    RANGE_INT,
    UPPER_INCLUSIVE,
    Path,
    PV,
    Range,
    compare_eq,
    compare_ge,
    compare_lt,
    from_plain,
    loose_eq,
)

P = Path.root()


def test_string_regex_eq_both_directions():
    s = PV.string(P, "aws:kms")
    r = PV.regex(P, "^aws:")
    assert compare_eq(s, r)
    assert compare_eq(r, s)
    assert not compare_eq(PV.string(P, "AES256"), r)


def test_int_float_not_comparable():
    # path_value.rs compare_values: int vs float is NotComparable
    with pytest.raises(NotComparableError):
        compare_eq(PV.int_(P, 1), PV.float_(P, 1.0))
    assert not loose_eq(PV.int_(P, 1), PV.float_(P, 1.0))


def test_range_membership():
    r = PV(P, RANGE_INT, Range(50, 200, LOWER_INCLUSIVE | UPPER_INCLUSIVE))
    assert compare_eq(PV.int_(P, 50), r)
    assert compare_eq(PV.int_(P, 200), r)
    assert not compare_eq(PV.int_(P, 201), r)
    half_open = PV(P, RANGE_INT, Range(100, 400, UPPER_INCLUSIVE))
    assert not compare_eq(PV.int_(P, 100), half_open)
    assert compare_eq(PV.int_(P, 101), half_open)


def test_deep_map_list_equality():
    a = from_plain({"a": [1, {"b": "x"}]})
    b = from_plain({"a": [1, {"b": "x"}]})
    c = from_plain({"a": [1, {"b": "y"}]})
    assert compare_eq(a, b)
    assert not compare_eq(a, c)


def test_list_order_matters():
    assert not compare_eq(from_plain([1, 2]), from_plain([2, 1]))


def test_ordering():
    assert compare_lt(PV.int_(P, 3), PV.int_(P, 5))
    assert compare_ge(PV.string(P, "b"), PV.string(P, "a"))
    with pytest.raises(NotComparableError):
        compare_lt(PV.string(P, "3"), PV.int_(P, 5))


def test_paths_from_plain():
    doc = from_plain({"Resources": {"b": {"Type": "T"}}})
    t = doc.val.values["Resources"].val.values["b"].val.values["Type"]
    assert t.self_path().s == "/Resources/b/Type"
