"""The frozen registry-scale corpus (corpus/rules, 249 rule files with
analytic expectation suites) replaces the unreachable AWS Guard Rules
Registry gate (`/root/reference/.github/workflows/pr.yml:131-200`):
every rule's own expectation suite must pass, every file must parse,
the vendored corpus must match its generator, and the device kernels
must agree with the oracle across the corpus inputs."""

import json
import os
import pathlib
import subprocess
import sys

import pytest
import yaml

from guard_tpu.cli import run
from guard_tpu.utils.io import Writer

REPO = pathlib.Path(__file__).resolve().parent.parent
CORPUS = REPO / "corpus" / "rules"

GUARD_FILES = sorted(CORPUS.glob("*.guard"))


def test_corpus_present_and_wide():
    assert len(GUARD_FILES) >= 200
    assert len(list((CORPUS / "tests").glob("*_tests.yaml"))) == len(GUARD_FILES)


def test_corpus_expectation_suites_pass():
    """`test -d corpus/rules` == the registry's own-suite gate."""
    w = Writer.buffered()
    code = run(["test", "-d", str(CORPUS)], writer=w)
    assert code == 0, w.stripped()[-2000:]


def test_corpus_parses_completely():
    """parse-tree over every corpus file (pr.yml:168-200 analogue)."""
    from guard_tpu.core.parser import parse_rules_file

    for g in GUARD_FILES:
        parse_rules_file(g.read_text(), g.name)  # must not raise


def test_corpus_matches_generator(tmp_path):
    """The vendored corpus IS the generator's output — no hand edits."""
    env = os.environ.copy()
    env["GUARD_TPU_CORPUS_OUT"] = str(tmp_path / "rules")
    subprocess.run(
        [sys.executable, str(REPO / "tools" / "gen_corpus.py")],
        check=True,
        env=env,
        capture_output=True,
    )
    fresh = sorted((tmp_path / "rules").rglob("*.*"))
    vendored = sorted(CORPUS.rglob("*.*"))
    assert [p.relative_to(tmp_path / "rules") for p in fresh] == [
        p.relative_to(CORPUS) for p in vendored
    ]
    for f, v in zip(fresh, vendored):
        assert f.read_text() == v.read_text(), v.name


def test_corpus_device_oracle_differential():
    """Every lowered corpus rule must produce oracle-identical statuses
    on its own suite inputs (the device-side half of the gate)."""
    from guard_tpu.core.parser import parse_rules_file
    from guard_tpu.core.scopes import RootScope
    from guard_tpu.core.evaluator import eval_rules_file
    from guard_tpu.core.values import from_plain
    from guard_tpu.commands.report import rule_statuses_from_root
    from guard_tpu.ops.encoder import encode_batch
    from guard_tpu.ops.ir import compile_rules_file
    from guard_tpu.ops.kernels import BatchEvaluator

    status_name = {0: "PASS", 1: "FAIL", 2: "SKIP"}
    checked = lowered_rules = host_rules = 0
    for g in GUARD_FILES:
        spec = yaml.safe_load(
            (CORPUS / "tests" / f"{g.stem}_tests.yaml").read_text()
        )
        docs_plain = [case.get("input") or {} for case in spec]
        rf = parse_rules_file(g.read_text(), g.name)
        docs = [from_plain(d) for d in docs_plain]
        batch, interner = encode_batch(docs)
        compiled = compile_rules_file(rf, interner)
        lowered_rules += len(compiled.rules)
        host_rules += len(compiled.host_rules)
        if not compiled.rules:
            continue
        evaluator = BatchEvaluator(compiled)
        statuses = evaluator(batch)
        unsure = evaluator.last_unsure
        for di, doc in enumerate(docs):
            scope = RootScope(rf, doc)
            eval_rules_file(rf, scope, None)
            oracle = {
                n: s.value
                for n, s in rule_statuses_from_root(
                    scope.reset_recorder().extract()
                ).items()
            }
            for ri, crule in enumerate(compiled.rules):
                if unsure is not None and bool(unsure[di, ri]):
                    continue
                dev = status_name[int(statuses[di, ri])]
                assert dev == oracle[crule.name], (
                    f"{g.name} doc {di} rule {crule.name}: "
                    f"device={dev} oracle={oracle[crule.name]}"
                )
                checked += 1
    # the corpus must meaningfully exercise the device path
    assert checked > 600, checked
    assert lowered_rules > host_rules * 5, (lowered_rules, host_rules)
