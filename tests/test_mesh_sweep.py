"""2-D (docs x packs) mesh plane suite (parallel/mesh2d.py): shape
grammar, contiguous doc-shard bounds under the MIN_DOCS floor, the
bounded shard prefetcher, and the production guarantees — a mesh sweep
must be byte-identical to the single-device escape hatch across output
modes, ship strictly fewer d2h bytes than the padded status matrix,
surface per-shard efficiency gauges that pass the metrics schema gate,
and scope the dispatch degradation ladder to the faulted shard (other
shards' documents never touch the host oracle)."""

import json

import numpy as np
import pytest

from guard_tpu.cli import run
from guard_tpu.parallel import ingest, mesh2d
from guard_tpu.parallel.mesh import PIPELINE_COUNTERS
from guard_tpu.utils import faults
from guard_tpu.utils.io import Reader, Writer

# two device-lowerable rule files that pack together (>= 2 compiled
# files is the packed-path precondition, and the mesh plane lives on
# the packed path)
RULES_A = (
    "let b = Resources.*[ Type == 'AWS::S3::Bucket' ]\n"
    "rule sse when %b !empty { %b.Properties.Enc == true }\n"
)
RULES_B = "rule sized { Resources.*.Size <= 100 }\n"


@pytest.fixture(autouse=True)
def _clean_mesh(monkeypatch):
    """Every test controls the mesh shape explicitly and starts with
    fresh fault state and no cached worker pools."""
    monkeypatch.delenv("GUARD_TPU_MESH", raising=False)
    monkeypatch.delenv("GUARD_TPU_MESH_MIN_DOCS", raising=False)
    monkeypatch.delenv("GUARD_TPU_FAULT", raising=False)
    monkeypatch.setenv("GUARD_TPU_RETRY_BACKOFF", "0")
    faults.reset_faults()
    ingest.close_shared_pools()
    yield
    ingest.close_shared_pools()
    faults.reset_faults()


def _doc(i, n=80, fail=()):
    return {
        "Resources": {
            "b": {
                "Type": "AWS::S3::Bucket",
                "Properties": {"Enc": i not in fail},
                "Size": 500 if i in fail else 50,
            }
        }
    }


def _mk_corpus(tmp_path, n=80, fail=(3, 71)):
    """n docs over two packable rule files. Files a...json sort before
    b...json, so under 2 contiguous doc shards the a-docs are shard 0
    and the b-docs are shard 1 — the prefix encodes the shard."""
    ra = tmp_path / "a.guard"
    ra.write_text(RULES_A)
    rb = tmp_path / "b.guard"
    rb.write_text(RULES_B)
    data = tmp_path / "data"
    data.mkdir(exist_ok=True)
    for i in range(n):
        prefix = "a" if i < n // 2 else "b"
        (data / f"{prefix}{i:03d}.json").write_text(
            json.dumps(_doc(i, n, fail))
        )
    return [str(ra), str(rb)], data


def _sweep(tmp_path, rules, data, *extra, tag="m", workers=0, chunk=80):
    w = Writer.buffered()
    rc = run(
        ["sweep", "-r", *rules, "-d", str(data),
         "-M", str(tmp_path / f"{tag}.jsonl"), "-c", str(chunk),
         "--backend", "tpu", "--ingest-workers", str(workers), *extra],
        writer=w, reader=Reader.from_string(""),
    )
    summary = json.loads(w.out.getvalue().strip().splitlines()[-1])
    summary.pop("manifest")
    return rc, summary


def _validate(rules, data, *extra):
    w = Writer.buffered()
    rc = run(
        ["validate", "-r", *rules, "-d", str(data),
         "--backend", "tpu", *extra],
        writer=w, reader=Reader.from_string(""),
    )
    return rc, w.out.getvalue(), w.err.getvalue()


# ------------------------------------------------------ shape grammar


def test_resolve_mesh_shape_grammar(monkeypatch):
    for off in ("off", "none", "0", "1", "1x1"):
        monkeypatch.setenv("GUARD_TPU_MESH", off)
        assert mesh2d.resolve_mesh_shape(8) is None
    for auto in ("", "auto", " AUTO "):
        monkeypatch.setenv("GUARD_TPU_MESH", auto)
        assert mesh2d.resolve_mesh_shape(8) == (2, 1)
        assert mesh2d.resolve_mesh_shape(1) is None
    monkeypatch.setenv("GUARD_TPU_MESH", "2x4")
    assert mesh2d.resolve_mesh_shape(8) == (2, 4)
    assert mesh2d.mesh_active(8)
    # more columns than devices: warn + legacy fallback, not a crash
    monkeypatch.setenv("GUARD_TPU_MESH", "4x16")
    assert mesh2d.resolve_mesh_shape(8) is None
    for bad in ("2x", "x2", "axb", "2x2x2", "0x2", "2x0"):
        monkeypatch.setenv("GUARD_TPU_MESH", bad)
        with pytest.raises(ValueError):
            mesh2d.resolve_mesh_shape(8)


def test_doc_shard_bounds_contiguous_and_floored(monkeypatch):
    # default floor 32: 100 docs split in two, 48 stay one shard,
    # 65 docs support only 2 floored shards even at r=4
    assert mesh2d.doc_shard_bounds(100, 2) == [(0, 50), (50, 100)]
    assert mesh2d.doc_shard_bounds(48, 2) == [(0, 48)]
    assert mesh2d.doc_shard_bounds(65, 4) == [(0, 33), (33, 65)]
    monkeypatch.setenv("GUARD_TPU_MESH_MIN_DOCS", "1")
    assert mesh2d.doc_shard_bounds(5, 2) == [(0, 3), (3, 5)]
    # bounds always partition [0, n) contiguously
    for n, r in ((7, 3), (64, 2), (257, 8)):
        bounds = mesh2d.doc_shard_bounds(n, r)
        assert bounds[0][0] == 0 and bounds[-1][1] == n
        assert all(a[1] == b[0] for a, b in zip(bounds, bounds[1:]))


def test_take_docs_slices_every_per_doc_column():
    from guard_tpu.core.values import from_plain
    from guard_tpu.ops.encoder import encode_batch

    docs = [from_plain(_doc(i, fail=(1,))) for i in range(6)]
    batch, _ = encode_batch(docs)
    # the full range returns the batch itself (no copy)
    assert mesh2d.take_docs(batch, 0, batch.n_docs) is batch
    sub = mesh2d.take_docs(batch, 2, 5)
    assert sub.n_docs == 3
    assert sub.n_nodes == batch.n_nodes
    np.testing.assert_array_equal(sub.node_kind, batch.node_kind[2:5])
    np.testing.assert_array_equal(sub.edge_valid, batch.edge_valid[2:5])
    np.testing.assert_array_equal(
        sub.node_key_id, batch.node_key_id[2:5]
    )


def test_assign_columns_balances_and_preserves_order():
    cols = mesh2d.assign_columns([5, 3, 2, 2], 2)
    assert len(cols) == 4 and set(cols) <= {0, 1}
    # greedy balance: the two column loads differ by at most the
    # smallest item
    loads = [0, 0]
    for load, c in zip([5, 3, 2, 2], cols):
        loads[c] += load
    assert abs(loads[0] - loads[1]) <= 2
    assert mesh2d.assign_columns([7], 1) == [0]
    assert mesh2d.assign_columns([], 4) == []


def test_column_mesh_partitions_devices():
    import jax

    devs = jax.devices()
    assert len(devs) == 8  # conftest forces the 8-device CPU mesh
    # C=1 spans everything = the default mesh (shared _SHARED_FNS keys)
    m1 = mesh2d.column_mesh((2, 1), 0)
    assert len(m1.devices.flatten()) == 8
    # C=2 partitions contiguously, 4 devices each, no overlap
    c0 = mesh2d.column_mesh((2, 2), 0)
    c1 = mesh2d.column_mesh((2, 2), 1)
    d0 = set(d.id for d in c0.devices.flatten())
    d1 = set(d.id for d in c1.devices.flatten())
    assert len(d0) == len(d1) == 4 and not (d0 & d1)


# -------------------------------------------------- shard prefetcher


def test_shard_prefetcher_matches_inline_split():
    from guard_tpu.core.values import from_plain
    from guard_tpu.ops.encoder import (
        NODE_BUCKETS_EXTENDED,
        encode_batch,
        split_batch_by_size,
    )

    docs = [from_plain(_doc(i)) for i in range(8)]
    batch, _ = encode_batch(docs)
    bounds = [(0, 4), (4, 8)]
    before = PIPELINE_COUNTERS["shards_prefetched"]
    got = list(ingest.ShardPrefetcher(
        batch, bounds, NODE_BUCKETS_EXTENDED
    ))
    assert PIPELINE_COUNTERS["shards_prefetched"] - before == 2
    assert [(s, lo) for s, lo, _g, _o in got] == [(0, 0), (1, 4)]
    for s, (lo, hi) in enumerate(bounds):
        want_groups, want_over = split_batch_by_size(
            mesh2d.take_docs(batch, lo, hi), NODE_BUCKETS_EXTENDED
        )
        _s, _lo, groups, oversize = got[s]
        np.testing.assert_array_equal(oversize, want_over)
        assert len(groups) == len(want_groups)
        for (sub, idx), (wsub, widx) in zip(groups, want_groups):
            np.testing.assert_array_equal(idx, widx)
            np.testing.assert_array_equal(sub.node_kind, wsub.node_kind)


def test_shard_prefetcher_propagates_producer_errors():
    class Boom:
        n_docs = 4

        def __getattr__(self, name):
            raise RuntimeError("poisoned batch")

    it = iter(ingest.ShardPrefetcher(Boom(), [(0, 2), (2, 4)], (64,)))
    with pytest.raises(RuntimeError, match="poisoned batch"):
        list(it)


# ------------------------------------------------- sweep/validate parity


@pytest.mark.parametrize("workers", [0, 2])
@pytest.mark.parametrize("shape", ["2x1", "2x2"])
def test_mesh_sweep_byte_identical_to_single_device(
    tmp_path, monkeypatch, shape, workers
):
    """The tentpole parity bar: the 2-D mesh sweep reproduces the
    single-device escape hatch byte-for-byte (summary minus manifest,
    exit code) and genuinely fans out (>1 shard prefetched)."""
    rules, data = _mk_corpus(tmp_path)
    monkeypatch.setenv("GUARD_TPU_MESH", "off")
    base = _sweep(tmp_path, rules, data, tag="base", workers=workers)
    monkeypatch.setenv("GUARD_TPU_MESH", shape)
    before = PIPELINE_COUNTERS["shards_prefetched"]
    got = _sweep(
        tmp_path, rules, data, tag=f"mesh{shape}-w{workers}",
        workers=workers,
    )
    assert got == base
    assert base[0] == 19  # the seeded failures genuinely fail
    assert PIPELINE_COUNTERS["shards_prefetched"] - before >= 2


@pytest.mark.parametrize(
    "mode",
    [
        [],
        ["-o", "yaml"],
        ["--structured", "-o", "json", "--show-summary", "none"],
        ["--structured", "-o", "junit", "--show-summary", "none"],
    ],
    ids=["console", "yaml", "json", "junit"],
)
def test_mesh_validate_byte_identical_across_output_modes(
    tmp_path, monkeypatch, mode
):
    rules, data = _mk_corpus(tmp_path)
    monkeypatch.setenv("GUARD_TPU_MESH", "off")
    base = _validate(rules, data, *mode)
    monkeypatch.setenv("GUARD_TPU_MESH", "2x2")
    got = _validate(rules, data, *mode)
    assert got == base
    assert base[0] == 19


def test_mesh_shape_flag_overrides_env(tmp_path, monkeypatch):
    """--mesh-shape is the CLI face of GUARD_TPU_MESH: `off` under an
    env-forced mesh must reproduce the escape hatch."""
    rules, data = _mk_corpus(tmp_path, n=68, fail=(2,))
    monkeypatch.setenv("GUARD_TPU_MESH", "off")
    base = _sweep(tmp_path, rules, data, tag="flag-base")
    monkeypatch.setenv("GUARD_TPU_MESH", "2x1")
    got = _sweep(
        tmp_path, rules, data, "--mesh-shape", "off", tag="flag-off"
    )
    assert got == base


# --------------------------------------- shard-scoped degradation


@pytest.mark.parametrize("workers", [0, 2])
@pytest.mark.parametrize("pack", ["1", "0"], ids=["packed", "perfile"])
def test_dispatch_fault_under_mesh_keeps_parity(
    tmp_path, monkeypatch, pack, workers
):
    """An injected dispatch fault under the mesh walks the degradation
    ladder for the faulted (shard, bucket) only — the run still
    reproduces the clean single-device output byte-for-byte."""
    rules, data = _mk_corpus(tmp_path)
    monkeypatch.setenv("GUARD_TPU_PACK", pack)
    monkeypatch.setenv("GUARD_TPU_MESH", "off")
    base = _sweep(
        tmp_path, rules, data, tag=f"fb{pack}-w{workers}",
        workers=workers,
    )
    monkeypatch.setenv("GUARD_TPU_MESH", "2x2")
    monkeypatch.setenv("GUARD_TPU_FAULT", "dispatch:nth=1")
    faults.reset_faults()
    got = _sweep(
        tmp_path, rules, data, tag=f"ff{pack}-w{workers}",
        workers=workers,
    )
    assert got == base
    assert faults.fault_stats()["dispatch_fallbacks"] >= 1


def test_shard_fault_never_sends_other_shards_to_oracle(
    tmp_path, monkeypatch
):
    """The shard boundary is the degradation boundary. The first
    dispatch fault lands on shard 0 (the a-docs); with the per-file
    retry rung ALSO killed, shard 0's bucket must land on the host
    oracle — and an armed oracle fault on every b-doc (shard 1) proves
    no other shard's document ever reaches that rung: if one did, the
    injected oracle fault would surface as a hard evaluation error."""
    from guard_tpu.parallel import mesh

    rules, data = _mk_corpus(tmp_path)
    # the oracle trap alone must be inert on a clean mesh run: no
    # document visits the oracle when every dispatch succeeds
    monkeypatch.setenv("GUARD_TPU_MESH", "off")
    base = _sweep(tmp_path, rules, data, tag="orc-base")
    monkeypatch.setenv("GUARD_TPU_MESH", "2x2")
    monkeypatch.setenv("GUARD_TPU_FAULT", "oracle:glob=b*")
    faults.reset_faults()
    clean = _sweep(tmp_path, rules, data, tag="orc-clean")
    assert clean == base

    # now fault shard 0's packed dispatch AND the per-file retry rung
    class _NoRetry:
        def __init__(self, *a, **k):
            raise RuntimeError("per-file rung disabled for test")

    monkeypatch.setattr(mesh, "ShardedBatchEvaluator", _NoRetry)
    monkeypatch.setenv(
        "GUARD_TPU_FAULT", "dispatch:nth=1,oracle:glob=b*"
    )
    faults.reset_faults()
    got = _sweep(tmp_path, rules, data, tag="orc-fault")
    assert got == base  # b-docs never tripped the oracle trap
    stats = faults.fault_stats()
    assert stats["dispatch_fallbacks"] >= 1
    assert stats["oracle_fallbacks"] >= 1  # shard 0 genuinely degraded
    assert stats.get("injected_oracle", 0) == 0


# ------------------------------------------- efficiency + schema


def test_mesh_shard_gauges_and_trimmed_d2h(tmp_path, monkeypatch):
    """A mesh sweep must surface schema-valid per-shard gauges and ship
    strictly fewer d2h bytes than the padded status protocol would."""
    import sys

    sys.path.insert(0, str(__import__("pathlib").Path(
        __file__).resolve().parent.parent / "tools"))
    from check_metrics_schema import check_snapshot

    from guard_tpu.ops.backend import efficiency_stats, reset_all_stats
    from guard_tpu.utils.telemetry import REGISTRY

    from guard_tpu.ops.backend import dispatch_stats

    rules, data = _mk_corpus(tmp_path)
    monkeypatch.setenv("GUARD_TPU_MESH", "off")
    reset_all_stats()
    _sweep(tmp_path, rules, data, tag="eff-off")
    off = efficiency_stats()
    off_collects = dispatch_stats()["dispatches"]
    monkeypatch.setenv("GUARD_TPU_MESH", "2x1")
    reset_all_stats()
    rc, _ = _sweep(tmp_path, rules, data, tag="gauges")
    assert rc == 19
    snap = REGISTRY.snapshot()
    gauges = snap["gauges"]
    for s in (0, 1):
        for g in ("doc_fill", "h2d", "d2h"):
            assert f"efficiency.shard_{s}.{g}" in gauges
        assert 0.0 < gauges[f"efficiency.shard_{s}.doc_fill"] <= 1.0
        assert gauges[f"efficiency.shard_{s}.d2h"] > 0
    assert check_snapshot(snap) == []
    eff = efficiency_stats()
    # the counters record actual transfers: trimmed never exceeds the
    # padded device shapes
    assert 0 < eff["device_to_host_bytes_trimmed"]
    assert (
        eff["device_to_host_bytes_trimmed"]
        <= eff["device_to_host_bytes"]
    )
    # the rim-only shrink is cross-leg (the bench's d2h claim): the
    # sweep profile ships 2 small reduced blocks per collect where the
    # off leg ships the full status/unsure matrices + all 7 rim blocks
    mesh_collects = dispatch_stats()["dispatches"]
    per_off = off["device_to_host_bytes"] / off_collects
    per_mesh = eff["device_to_host_bytes"] / mesh_collects
    assert per_mesh * 4 <= per_off


def test_plan_cache_hits_under_mesh(tmp_path, monkeypatch):
    """Shard plans hit the compiled-plan memo: the device count is in
    the cache key, so a second identical mesh sweep re-lowers nothing."""
    from guard_tpu.ops.plan import plan_stats, reset_plan_stats

    rules, data = _mk_corpus(tmp_path, n=68, fail=(2,))
    monkeypatch.setenv("GUARD_TPU_MESH", "2x1")
    monkeypatch.setenv("GUARD_TPU_PLAN_CACHE_DIR", str(tmp_path / "pl"))
    _sweep(tmp_path, rules, data, tag="p1")
    reset_plan_stats()
    _sweep(tmp_path, rules, data, tag="p2")
    stats = plan_stats()
    assert stats["hits"] >= 1
    assert stats["misses"] == 0
