"""Operations-plane suite (PR 8): the persistent run ledger
(utils/ledger.py + `guard-tpu report`), the always-on flight recorder
(telemetry ring buffer + abnormal-exit dumps), and the
hardware-efficiency counter group — plus the Histogram.quantile edge
cases and the bucket-label monotonicity gate that rode along.

The invariants: the recorder must never change report bytes or exit
codes; the ledger must never write unless GUARD_TPU_LEDGER_DIR is set;
the efficiency counters must reconcile EXACTLY with hand-computed
batch shapes, not approximately."""

import json
import os
import pathlib
import sys

import numpy as np
import pytest

from guard_tpu.cli import run
from guard_tpu.core.parser import parse_rules_file
from guard_tpu.core.values import from_plain
from guard_tpu.ops import backend
from guard_tpu.ops.encoder import encode_batch
from guard_tpu.ops.ir import compile_rules_file, pack_compatible
from guard_tpu.parallel import ingest
from guard_tpu.parallel.mesh import ShardedBatchEvaluator, pad_to_multiple
from guard_tpu.utils import ledger, telemetry
from guard_tpu.utils.io import Reader, Writer

sys.path.insert(
    0, str(pathlib.Path(__file__).resolve().parent.parent / "tools")
)

from check_metrics_schema import _check_bucket_labels, check_snapshot  # noqa: E402
from perf_ledger import backfill  # noqa: E402

RULES = (
    "let b = Resources.*[ Type == 'AWS::S3::Bucket' ]\n"
    "rule sse when %b !empty { %b.Properties.Enc == true }\n"
)

_ENV_KEYS = (
    "GUARD_TPU_FLIGHT_RECORDER",
    "GUARD_TPU_FLIGHTREC_DIR",
    "GUARD_TPU_LEDGER_DIR",
)


@pytest.fixture(autouse=True)
def _clean_planes():
    """Every test starts and ends with tracing off, a disarmed flight
    recorder (conftest pins GUARD_TPU_FLIGHT_RECORDER=0), an empty
    ring, a zeroed registry and no ledger destination. Env mutations
    are restored HERE (not via monkeypatch) so flightrec_refresh()
    runs after the restore, never before it."""
    saved = {k: os.environ.get(k) for k in _ENV_KEYS}
    os.environ.pop("GUARD_TPU_LEDGER_DIR", None)
    telemetry.disable()
    telemetry.reset_trace()
    telemetry.REGISTRY.reset(include_persistent=True)
    telemetry.flightrec_refresh()
    telemetry.flightrec_reset()
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    telemetry.flightrec_refresh()
    telemetry.flightrec_reset()
    telemetry.disable()
    telemetry.reset_trace()
    telemetry.REGISTRY.reset(include_persistent=True)


def _arm_flightrec(tmp_path) -> None:
    os.environ["GUARD_TPU_FLIGHT_RECORDER"] = "1"
    os.environ["GUARD_TPU_FLIGHTREC_DIR"] = str(tmp_path)
    telemetry.flightrec_refresh()
    telemetry.flightrec_reset()


def _mk_corpus(tmp_path, n=8, fail=(2,)):
    rules = tmp_path / "rules.guard"
    rules.write_text(RULES)
    data = tmp_path / "data"
    data.mkdir(exist_ok=True)
    for i in range(n):
        doc = {
            "Resources": {
                "b": {
                    "Type": "AWS::S3::Bucket",
                    "Properties": {"Enc": i not in fail},
                }
            }
        }
        (data / f"t{i:02d}.json").write_text(json.dumps(doc))
    return rules, data


def _cli(*argv):
    w = Writer.buffered()
    rc = run(list(argv), writer=w, reader=Reader())
    return rc, w.out.getvalue(), w.err.getvalue()


# ------------------------------------------------- quantile edge cases


def test_quantile_empty_histogram_returns_none():
    h = telemetry.Histogram("empty")
    assert h.quantile(0.5) is None
    assert h.quantile(0.99) is None
    snap = h.snapshot()
    assert snap["count"] == 0
    assert snap["p50_seconds"] is None


def test_quantile_single_observation_is_exact():
    h = telemetry.Histogram("one")
    h.observe(0.001)
    # a single sample IS every quantile: the bucket upper bound must
    # clamp to the observed max, not report 2^-9
    assert h.quantile(0.5) == 0.001
    assert h.quantile(0.99) == 0.001
    assert h.quantile(1.0) == 0.001


def test_quantile_zero_returns_min_and_one_returns_max():
    h = telemetry.Histogram("spread")
    for v in (0.002, 0.5, 4.0):
        h.observe(v)
    assert h.quantile(0.0) == 0.002
    assert h.quantile(1.0) == 4.0


def test_quantile_overflow_bucket_clamps_to_max():
    h = telemetry.Histogram("huge")
    h.observe(1e9)  # beyond 2^LOG2_HI: lands in the inf bucket
    assert h.quantile(0.5) == 1e9
    assert h.snapshot()["buckets"]["inf"] == 1


# -------------------------------------------- bucket-label schema gate


def test_bucket_label_gate_accepts_live_snapshot():
    telemetry.REGISTRY.histogram("stagey").observe(0.01)
    snap = telemetry.metrics_snapshot()
    assert check_snapshot(snap) == []


def test_bucket_label_gate_rejects_scrambled_order():
    bad = {"le_2^-3s": 1, "le_2^-5s": 0, "inf": 0}
    problems = _check_bucket_labels("h", bad)
    assert any("not monotonically ordered" in p for p in problems)


def test_bucket_label_gate_rejects_misplaced_inf_and_garbage():
    assert any(
        "'inf' bucket is not last" in p
        for p in _check_bucket_labels("h", {"inf": 0, "le_2^-3s": 1})
    )
    assert any(
        "malformed bucket label" in p
        for p in _check_bucket_labels("h", {"le_2pow3s": 1})
    )


# ------------------------------------------------ flight recorder ring


def test_ring_wraps_and_keeps_newest_in_seq_order():
    fr = telemetry._FlightRecorder(capacity=4)
    for i in range(10):
        fr.record("i", f"ev{i}", "events", float(i), 0.0, None)
    assert fr.written == 10
    snap = fr.snapshot()
    assert len(snap) == 4
    assert [s[0] for s in snap] == [7, 8, 9, 10]
    assert [s[2] for s in snap] == ["ev6", "ev7", "ev8", "ev9"]


def test_armed_recorder_feeds_ring_and_registry_without_tracing(tmp_path):
    _arm_flightrec(tmp_path)
    with telemetry.span("encode", {"docs": 3}):
        pass
    telemetry.event("fault.retries", {"n": 1})
    assert not telemetry.enabled()  # tracing stayed off
    # trace buffer untouched (metadata rows aside, which are static)
    assert all(
        e.get("ph") == "M" for e in telemetry.trace_events()
    )
    snap = telemetry._FLIGHTREC.snapshot()
    assert [(s[1], s[2]) for s in snap] == [
        ("X", "encode"), ("i", "fault.retries"),
    ]
    # the dump's metrics section carries the stage story
    assert telemetry.REGISTRY.span_rollups()["encode"]["count"] == 1
    assert telemetry._FLIGHTREC.fault_seen  # fault.* latched the dump


def test_disarmed_recorder_is_inert():
    assert not telemetry.flightrec_enabled()
    with telemetry.span("encode"):
        pass
    telemetry.event("fault.retries", {"n": 1})
    assert telemetry._FLIGHTREC.written == 0
    assert telemetry.flightrec_dump("test") is None
    assert telemetry.flightrec_on_exit(5) is None


def test_flightrec_dump_schema_and_determinism(tmp_path):
    _arm_flightrec(tmp_path)
    with telemetry.span("encode"):
        pass
    telemetry.flightrec_mark_fault(
        "serve.request_error", {"error_class": "ValueError"}
    )
    p1 = telemetry.flightrec_dump("test", path=str(tmp_path / "a.json"))
    p2 = telemetry.flightrec_dump("test", path=str(tmp_path / "b.json"))
    d1 = json.loads(pathlib.Path(p1).read_text())
    d2 = json.loads(pathlib.Path(p2).read_text())
    # two dumps of the same ring are event-identical (ts normalized to
    # the oldest retained record, not to dump time)
    assert d1["traceEvents"] == d2["traceEvents"]
    other = d1["otherData"]
    assert other["schema_version"] == telemetry.SCHEMA_VERSION
    assert other["reason"] == "test"
    assert other["records_written"] == 2
    assert other["ring_capacity"] == telemetry._FLIGHTREC.capacity
    assert check_snapshot(d1["metrics"]) == []
    names = {
        e["name"] for e in d1["traceEvents"] if e.get("ph") == "i"
    }
    assert "serve.request_error" in names


def test_cli_exit_code_5_triggers_dump_without_trace_out(tmp_path):
    _arm_flightrec(tmp_path)
    rc, _out, err = _cli(
        "validate", "-r", str(tmp_path / "nope.guard"),
        "-d", str(tmp_path), "--backend", "tpu",
    )
    assert rc == 5
    dumps = sorted(tmp_path.glob("flightrec-*.json"))
    assert len(dumps) == 1
    doc = json.loads(dumps[0].read_text())
    assert doc["otherData"]["reason"] == "exit_code_5"
    assert check_snapshot(doc["metrics"]) == []


@pytest.mark.parametrize("workers", [0, 2])
@pytest.mark.parametrize("pack", [(), ("--no-pack",)])
def test_recorder_leaves_report_bytes_identical(tmp_path, workers, pack):
    ingest.close_shared_pools()
    try:
        rules, data = _mk_corpus(tmp_path, n=8, fail=(2, 5))
        common = (
            "validate", "-r", str(rules), "-d", str(data),
            "--backend", "tpu", "--ingest-workers", str(workers), *pack,
        )
        os.environ["GUARD_TPU_FLIGHT_RECORDER"] = "0"
        os.environ["GUARD_TPU_FLIGHTREC_DIR"] = str(tmp_path)
        telemetry.flightrec_refresh()
        off_rc, off_out, _ = _cli(*common)
        _arm_flightrec(tmp_path)
        on_rc, on_out, _ = _cli(*common)
        assert (on_rc, on_out) == (off_rc, off_out)
        assert off_rc == 19  # failing docs: FAILURE, not an error exit
        # a normal (non-5, fault-free) exit leaves no dump behind
        assert sorted(tmp_path.glob("flightrec-*.json")) == []
        assert telemetry._FLIGHTREC.written > 0  # but the ring saw spans
    finally:
        ingest.close_shared_pools()


# ------------------------------------------------------------- ledger


def test_ledger_append_and_roundtrip(tmp_path):
    os.environ["GUARD_TPU_LEDGER_DIR"] = str(tmp_path)
    rec = ledger.append_record(
        "validate",
        headline={"metric": "docs_per_sec", "value": 100.0, "unit": "docs/sec"},
        config={"backend": "tpu", "chunk_size": 64},
        exit_code=0,
    )
    assert ledger.check_record(rec) == []
    recs = ledger.read_ledger()
    assert len(recs) == 1
    assert ledger.check_record(recs[0]) == []
    assert recs[0]["kind"] == "validate"
    assert recs[0]["schema_version"] == ledger.LEDGER_SCHEMA_VERSION
    assert len(recs[0]["config_hash"]) == 16
    assert isinstance(recs[0]["metrics"], dict)


def test_config_hash_is_key_order_stable():
    a = ledger.config_hash({"a": 1, "b": [2, 3]})
    b = ledger.config_hash({"b": [2, 3], "a": 1})
    assert a == b
    assert ledger.config_hash({"a": 1, "b": [2, 4]}) != a


def test_unconfigured_ledger_writes_nothing():
    assert not ledger.ledger_enabled()
    assert ledger.append_record("validate") is None
    with pytest.raises(FileNotFoundError):
        ledger.read_ledger()


def test_corrupt_ledger_line_raises_with_line_number(tmp_path):
    p = tmp_path / "ledger.jsonl"
    p.write_text('{"ok": 1}\n{corrupt\n')
    with pytest.raises(ValueError, match=":2:"):
        ledger.read_ledger(str(p))


def _rec(value, metric="tps", unit="templates/sec", counters=None):
    r = ledger.build_record(
        "bench",
        headline={"metric": metric, "value": value, "unit": unit},
        capture_metrics=False,
    )
    if counters is not None:
        r["metrics"] = {"counters": counters}
    return r


def test_diff_records_ratio_and_counter_deltas():
    a = _rec(100.0, counters={"dispatch": {"dispatches": 4}})
    b = _rec(110.0, counters={"dispatch": {"dispatches": 6}})
    d = ledger.diff_records(a, b)
    assert d["headline_ratio"] == pytest.approx(1.1)
    assert d["counters"] == {"dispatch.dispatches": {"a": 4, "b": 6}}
    assert not d["same_config"]  # neither record carries a config hash


def test_regression_check_parity_regression_and_direction():
    recs = [_rec(100.0), _rec(101.0), _rec(99.0)]
    assert ledger.regression_check(recs, "tps")["status"] == "ok"
    regressed = ledger.regression_check(recs + [_rec(79.0)], "tps")
    assert regressed["status"] == "regressed"
    assert regressed["baseline"] == 101.0  # best-of-window, not last
    # seconds-unit metrics are lower-is-better
    lat = [_rec(10.0, "p99", "seconds"), _rec(13.0, "p99", "seconds")]
    assert ledger.regression_check(lat, "p99")["status"] == "regressed"
    assert ledger.regression_check(lat, "p99")["lower_is_better"]


def test_regression_check_insufficient_records():
    v = ledger.regression_check([_rec(100.0)], "tps")
    assert v["status"] == "insufficient"
    assert not v["regressed"]


def test_backfill_ingests_bench_artifact_rows(tmp_path):
    art = tmp_path / "bench_all_r11.json"
    art.write_text(
        json.dumps({"metric": "m1", "value": 10.0, "unit": "u",
                    "vs_baseline": 1.0}) + "\n"
        + json.dumps({"metric": "m2", "value": 20.0, "unit": "u",
                      "vs_baseline": 2.0}) + "\n"
    )
    dest = tmp_path / "ledger.jsonl"
    assert backfill([art], ledger_file=str(dest)) == 2
    recs = ledger.read_ledger(str(dest))
    assert [r["headline"]["metric"] for r in recs] == ["m1", "m2"]
    for r in recs:
        assert ledger.check_record(r) == []
        assert r["kind"] == "bench"
        assert r["extra"]["backfilled"] is True
        assert r["extra"]["round"] == 11
        assert r["metrics"] is None  # no fake snapshot for history


# --------------------------------------------------- report subcommand


def test_report_diffs_two_newest_records(tmp_path):
    os.environ["GUARD_TPU_LEDGER_DIR"] = str(tmp_path)
    for v in (100.0, 98.0):
        ledger.append_record(
            "bench",
            headline={"metric": "tps", "value": v, "unit": "templates/sec"},
            config={"backend": "tpu"},
        )
    rc, out, _ = _cli("report")
    assert rc == 0
    assert "previous:" in out and "newest:" in out
    assert "headline ratio: x0.980" in out
    assert "same config" in out


def test_report_check_gates_regressions(tmp_path):
    os.environ["GUARD_TPU_LEDGER_DIR"] = str(tmp_path)
    for v in (100.0, 99.0):
        ledger.append_record(
            "bench",
            headline={"metric": "tps", "value": v, "unit": "templates/sec"},
        )
    rc, out, _ = _cli("report", "--check", "tps")
    assert rc == 0 and "ok" in out
    ledger.append_record(
        "bench",
        headline={"metric": "tps", "value": 80.0, "unit": "templates/sec"},
    )
    rc, out, _ = _cli("report", "--check", "tps")
    assert rc == 19
    assert "regressed" in out


def test_report_error_exits(tmp_path):
    # no ledger configured at all
    rc, _out, err = _cli("report")
    assert rc == 5 and "Error" in err
    # configured but too few records to diff
    os.environ["GUARD_TPU_LEDGER_DIR"] = str(tmp_path)
    ledger.append_record("bench", headline={
        "metric": "tps", "value": 1.0, "unit": "templates/sec"})
    rc, _out, err = _cli("report")
    assert rc == 5 and "at least 2" in err
    # a record without a metrics snapshot (backfilled history) cannot
    # render the efficiency view
    ledger.append_record("bench", headline={
        "metric": "tps", "value": 1.0, "unit": "templates/sec"},
        capture_metrics=False)
    rc, _out, err = _cli("report", "--efficiency")
    assert rc == 5 and "no efficiency metrics" in err


def test_report_efficiency_renders_utilization(tmp_path):
    os.environ["GUARD_TPU_LEDGER_DIR"] = str(tmp_path)
    backend.reset_efficiency_stats()
    docs = [_doc(i) for i in range(3)]
    batch, interner = encode_batch(docs)
    compiled = compile_rules_file(
        parse_rules_file(RULES, "r.guard"), interner
    )
    ev = ShardedBatchEvaluator(compiled)
    ev.collect(ev.dispatch(batch))
    ledger.append_record("validate", exit_code=0)
    rc, out, _ = _cli("report", "--efficiency")
    assert rc == 0
    assert "efficiency.docs_real: 3" in out
    assert "doc slot fill:" in out and "node slot fill:" in out


def test_report_efficiency_renders_result_cache_story(tmp_path):
    """The incremental plane's face in `report --efficiency`: the
    hit rate comes from the captured result_cache counter group, the
    delta fraction from the session record's extra block."""
    from guard_tpu.cache import results as rcache

    os.environ["GUARD_TPU_LEDGER_DIR"] = str(tmp_path)
    rcache.reset_result_cache_stats()
    rcache.RESULT_COUNTERS["hits"] += 3
    rcache.RESULT_COUNTERS["misses"] += 1
    ledger.append_record(
        "validate", exit_code=0,
        extra={"delta_docs": 1, "total_docs": 4, "delta_fraction": 0.25},
    )
    rcache.reset_result_cache_stats()
    rc, out, _ = _cli("report", "--efficiency")
    assert rc == 0
    assert "result-cache hit rate: 75.0% (3/4 lookups)" in out
    assert "delta fraction: 25.0% (1/4 docs dispatched)" in out


def test_session_epilogue_records_delta_fraction(tmp_path, monkeypatch):
    """A tpu validate session that partitioned against the result
    cache carries its delta fraction in the ledger record's extra."""
    monkeypatch.setenv("GUARD_TPU_RESULT_CACHE", "1")
    monkeypatch.setenv(
        "GUARD_TPU_RESULT_CACHE_DIR", str(tmp_path / "rcache")
    )
    os.environ["GUARD_TPU_LEDGER_DIR"] = str(tmp_path)
    rules, data = _mk_corpus(tmp_path, n=4, fail=())
    args = ("validate", "-r", str(rules), "-d", str(data),
            "--backend", "tpu")
    rc, _out, _err = _cli(*args)
    assert rc == 0
    rc, _out, _err = _cli(*args)  # warm: all 4 docs replay
    assert rc == 0
    recs = ledger.read_ledger()
    assert recs[-2]["extra"]["delta_fraction"] == 1.0
    assert recs[-1]["extra"] == {
        "delta_docs": 0, "total_docs": 4, "delta_fraction": 0.0
    }


def test_session_epilogue_appends_one_record_per_session(tmp_path):
    os.environ["GUARD_TPU_LEDGER_DIR"] = str(tmp_path)
    rules, data = _mk_corpus(tmp_path, n=4, fail=())
    rc, _out, _err = _cli(
        "validate", "-r", str(rules), "-d", str(data), "--backend", "tpu",
    )
    assert rc == 0
    recs = ledger.read_ledger()
    assert len(recs) == 1
    rec = recs[0]
    assert ledger.check_record(rec) == []
    assert rec["kind"] == "validate"
    assert rec["exit_code"] == 0
    assert rec["headline"]["metric"] == "validate_session_seconds"
    assert rec["config_hash"] is not None


# ------------------------------------------------- efficiency metrics


def _doc(i: int, ok: bool = True):
    return from_plain({
        "Resources": {
            "b": {
                "Type": "AWS::S3::Bucket",
                "Properties": {"Enc": ok if i % 2 == 0 else True},
            }
        }
    })


def test_efficiency_counters_reconcile_with_batch_shapes():
    backend.reset_efficiency_stats()
    docs = [_doc(i) for i in range(3)]
    batch, interner = encode_batch(docs)
    compiled = compile_rules_file(
        parse_rules_file(RULES, "r.guard"), interner
    )
    ev = ShardedBatchEvaluator(compiled)
    ev.collect(ev.dispatch(batch))
    stats = backend.efficiency_stats()

    # hand-compute the same shapes the dispatch saw
    arrays, d = pad_to_multiple(
        compiled.device_arrays(batch), ev.mesh.devices.size
    )
    padded_d, n_nodes = arrays["node_kind"].shape
    real_slots = int((arrays["node_kind"] >= 0).sum())
    assert d == 3
    assert stats["docs_real"] == 3
    assert stats["docs_padded"] == padded_d - 3
    assert stats["node_slots_real"] == real_slots
    assert stats["node_slots_padded"] == padded_d * n_nodes - real_slots
    expected_h2d = int(
        sum(a.nbytes for a in arrays.values())
        + compiled.lit_values().nbytes
    )
    assert stats["host_to_device_bytes"] == expected_h2d
    # d2h: the PADDED status matrix (int8) crosses back, plus the
    # unsure bitmap when the rule file compares against query RHS
    n_rules = len(compiled.rules)
    expected_d2h = padded_d * n_rules
    if compiled.needs_unsure:
        expected_d2h += padded_d * n_rules
    assert stats["device_to_host_bytes"] == expected_d2h

    gauges = telemetry.metrics_snapshot()["gauges"]
    assert gauges[f"efficiency.bucket_{n_nodes}.doc_fill"] == (
        pytest.approx(3 / padded_d)
    )
    assert gauges[f"efficiency.bucket_{n_nodes}.node_fill"] == (
        pytest.approx(real_slots / (padded_d * n_nodes))
    )
    assert gauges["efficiency.live_executables"] >= 1


def test_pack_slot_utilization_gauge_matches_counters():
    backend.reset_efficiency_stats()
    docs = [_doc(i) for i in range(4)]
    batch, interner = encode_batch(docs)
    rf_b = parse_rules_file(
        "rule always_pass { Resources exists }\n", "r2.guard"
    )
    compiled_files = [
        compile_rules_file(parse_rules_file(RULES, "r1.guard"), interner),
        compile_rules_file(rf_b, interner),
    ]
    items = [
        (fi, c)
        for fi, c in enumerate(compiled_files)
        if pack_compatible(c) is None
    ]
    assert len(items) == 2
    backend._evaluate_packs(items, batch)
    stats = backend.efficiency_stats()
    used = stats["pack_rule_slots_used"]
    cap = stats["pack_rule_slots_capacity"]
    assert used == sum(len(c.rules) for _fi, c in items)
    assert cap > 0 and cap % backend.PACK_MAX_RULES == 0
    util = telemetry.metrics_snapshot()["gauges"][
        "efficiency.pack_slot_utilization"
    ]
    assert util == pytest.approx(used / cap)
