"""Systematic (LHS kind x operator x RHS literal kind) differential
matrix: every combination evaluates on the device kernels AND the CPU
oracle and must agree bit-for-bit. This densely pins the reference's
comparison semantics (path_value.rs:1047-1191 typed compares,
operators.rs EqOperation/InOperation/CommonOperator, the
NotComparable-survives-`not` rule, and unary op outcomes,
eval.rs:174-405) across the kernel's exact numeric keys, regex bit
columns, string ordering tables and struct ids."""

import pytest

from guard_tpu.core.parser import parse_rules_file
from guard_tpu.core.scopes import RootScope
from guard_tpu.core.evaluator import eval_rules_file
from guard_tpu.core.values import from_plain
from guard_tpu.ops.encoder import encode_batch
from guard_tpu.ops.ir import compile_rules_file
from guard_tpu.ops.kernels import BatchEvaluator

STATUS = {0: "PASS", 1: "FAIL", 2: "SKIP"}

# one document per LHS shape; `missing` exercises UnResolved paths
LHS_DOCS = {
    "str": {"Props": {"v": "a"}},
    "str_empty": {"Props": {"v": ""}},
    "str_num": {"Props": {"v": "1"}},
    "int0": {"Props": {"v": 0}},
    "int1": {"Props": {"v": 1}},
    "int_big": {"Props": {"v": 16777217}},  # 2^24 + 1: f32 would collide
    "float": {"Props": {"v": 1.5}},
    "float_whole": {"Props": {"v": 1.0}},
    "bool_t": {"Props": {"v": True}},
    "bool_f": {"Props": {"v": False}},
    "null": {"Props": {"v": None}},
    "list_int": {"Props": {"v": [0, 1]}},
    "list_str": {"Props": {"v": ["a", "b"]}},
    "list_empty": {"Props": {"v": []}},
    "map": {"Props": {"v": {"k": 1}}},
    "map_empty": {"Props": {"v": {}}},
    "missing": {"Props": {"w": 0}},
}

RHS_LITERALS = [
    "'a'",
    "''",
    "'1'",
    "/a/",
    "/^$/",
    "0",
    "1",
    "16777217",
    "16777216",  # the f32-colliding neighbor
    "1.0",
    "1.5",
    "true",
    "false",
    "null",
    "r(0,2)",
    "r[0,1]",
    "r(0.5, 1.5]",
    "['a', 'b']",
    "[0, 1]",
    "[1]",
    "[]",
    "{ 'k': 1 }",
]

BINARY_OPS = ["==", "!=", ">", ">=", "<", "<=", "in", "not in"]
UNARY_OPS = [
    "exists", "!exists", "empty", "!empty", "is_string", "is_list",
    "is_struct", "is_int", "is_float", "is_bool", "is_null",
]


def _oracle(rf, doc):
    """Rule statuses, or None when the oracle RAISES for this doc
    (e.g. elementwise `empty` on an int, eval.rs IncompatibleError) —
    the kernel must then have flagged the doc unsure so the backend
    reruns it and reproduces the reference's error path."""
    from guard_tpu.core.errors import GuardError
    from guard_tpu.commands.report import rule_statuses_from_root

    scope = RootScope(rf, doc)
    try:
        eval_rules_file(rf, scope, None)
    except GuardError:
        return None
    root = scope.reset_recorder().extract()
    return {n: s.value for n, s in rule_statuses_from_root(root).items()}


def _run_matrix(rules_text):
    rf = parse_rules_file(rules_text, "matrix.guard")
    docs = [from_plain(d) for d in LHS_DOCS.values()]
    batch, interner = encode_batch(docs)
    compiled = compile_rules_file(rf, interner)
    # documented host fallbacks (struct literals outside plain ==) are
    # allowed — they evaluate on the oracle by design; everything that
    # DID lower must agree with it
    evaluator = BatchEvaluator(compiled)
    statuses = evaluator(batch)
    unsure = evaluator.last_unsure
    mismatches = []
    for di, (lhs_name, doc_plain) in enumerate(LHS_DOCS.items()):
        oracle = _oracle(rf, docs[di])
        if oracle is None:
            # oracle raises for this doc: the kernel must have flagged
            # it unsure on some rule (forcing the backend rerun that
            # surfaces the error)
            if unsure is None or not bool(unsure[di].any()):
                mismatches.append(
                    f"lhs={lhs_name}: oracle raises but no unsure flag"
                )
            continue
        for ri, crule in enumerate(compiled.rules):
            if unsure is not None and bool(unsure[di, ri]):
                continue  # oracle-routed by design (e.g. list-in-list)
            dev = STATUS[int(statuses[di, ri])]
            if dev != oracle[crule.name]:
                mismatches.append(
                    f"lhs={lhs_name} {crule.name}: device={dev} "
                    f"oracle={oracle[crule.name]}"
                )
    assert not mismatches, "\n".join(mismatches[:25])


@pytest.mark.parametrize("op", BINARY_OPS)
def test_binary_matrix(op):
    rules = []
    for j, rhs in enumerate(RHS_LITERALS):
        rules.append(f"rule r{j} when Props exists {{ Props.v {op} {rhs} }}")
        rules.append(
            f"rule s{j} when Props exists {{ some Props.v {op} {rhs} }}"
        )
    _run_matrix("\n".join(rules))


def test_unary_matrix():
    rules = []
    for j, op in enumerate(UNARY_OPS):
        rules.append(f"rule r{j} when Props exists {{ Props.v {op} }}")
        rules.append(f"rule s{j} when Props exists {{ some Props.v {op} }}")
        if not op.startswith("!"):
            rules.append(
                f"rule n{j} when Props exists {{ not Props.v {op} }}"
            )
    _run_matrix("\n".join(rules))


def test_query_rhs_matrix():
    # every binary op against a query RHS resolving to each RHS shape
    rules = []
    for j, op in enumerate(BINARY_OPS):
        rules.append(f"rule q{j} when Props exists {{ Props.v {op} Props.r }}")
    docs = []
    names = []
    for lhs_name, lhs_doc in LHS_DOCS.items():
        for r in ("a", 1, 1.5, True, None, [0, 1], {"k": 1}):
            d = {"Props": dict(lhs_doc["Props"])}
            d["Props"]["r"] = r
            docs.append(d)
            names.append(f"{lhs_name}-vs-{r!r}")
    rf = parse_rules_file("\n".join(rules), "qmatrix.guard")
    pv_docs = [from_plain(d) for d in docs]
    batch, interner = encode_batch(pv_docs)
    compiled = compile_rules_file(rf, interner)
    assert not compiled.host_rules
    evaluator = BatchEvaluator(compiled)
    statuses = evaluator(batch)
    unsure = evaluator.last_unsure
    mismatches = []
    for di, name in enumerate(names):
        oracle = _oracle(rf, pv_docs[di])
        for ri, crule in enumerate(compiled.rules):
            if unsure is not None and bool(unsure[di, ri]):
                continue
            dev = STATUS[int(statuses[di, ri])]
            if dev != oracle[crule.name]:
                mismatches.append(
                    f"{name} {crule.name}: device={dev} "
                    f"oracle={oracle[crule.name]}"
                )
    assert not mismatches, "\n".join(mismatches[:25])
