"""The driver-contract dry run must be hermetic: it runs in a fresh
subprocess WITHOUT conftest.py's JAX_PLATFORMS=cpu forcing, on a host
whose default JAX backend may be a (possibly wedged) TPU tunnel. The
dry run must pick the virtual CPU mesh and never commit an array to
the default device (VERDICT round 1, item 1)."""

import os
import pathlib
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent


# platform=None: default platform untouched (may resolve to a TPU
# backend). platform="cpu": the env var is set but NOT honored on hosts
# whose TPU plugin self-registers (axon). platform="axon": the ambient
# environment names a TPU plugin outright — the real driver host does
# exactly this — and the dry run must still force CPU programmatically.
# with_flag=False: XLA_FLAGS carries no device-count flag at all; the
# dry run must inject it itself before backend init.
@pytest.mark.parametrize(
    "platform,with_flag",
    [(None, True), ("cpu", True), ("axon", True), (None, False), ("axon", False)],
)
def test_dryrun_multichip_subprocess_no_platform_forcing(platform, with_flag):
    env = os.environ.copy()
    env.pop("JAX_PLATFORMS", None)
    if platform is not None:
        env["JAX_PLATFORMS"] = platform
    flags = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    if with_flag:
        flags.append("--xla_force_host_platform_device_count=8")
    env["XLA_FLAGS"] = " ".join(flags)
    proc = subprocess.run(
        [
            sys.executable,
            "-c",
            "from __graft_entry__ import dryrun_multichip; dryrun_multichip(8)",
        ],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=420,
    )
    assert proc.returncode == 0, proc.stderr[-4000:]
    # the platform in the OK line proves the run was hermetic: a
    # regression to real TPU devices would also print "OK" on a
    # healthy multi-chip host, but not with cpu devices
    assert "dryrun_multichip OK: 8 cpu devices" in proc.stdout


def test_dryrun_multichip_in_process():
    # under conftest's 8-device CPU mesh this must also just work
    sys.path.insert(0, str(REPO))
    from __graft_entry__ import dryrun_multichip

    dryrun_multichip(8)


def test_packed_group_sharding_dryrun_speedup():
    """Config 5c's measurement flow on the 8-device CPU dryrun: packs
    as the unit of rule-axis sharding (PackShardedEvaluator), every
    group dispatched before any collection, against the serial
    dispatch-and-collect-per-file loop on the same workload. Asserts
    bit-parity and REPORTS the packed-group speedup (virtual CPU
    devices share host cores, so the wall-clock ratio is reported, not
    asserted — on real hardware the groups execute concurrently)."""
    import time

    import numpy as np

    sys.path.insert(0, str(REPO))
    import bench
    from guard_tpu.core.parser import parse_rules_file
    from guard_tpu.core.values import from_plain
    from guard_tpu.ops.encoder import encode_batch
    from guard_tpu.ops.ir import compile_rules_file
    from guard_tpu.parallel.mesh import ShardedBatchEvaluator
    from guard_tpu.parallel.rules import PackShardedEvaluator

    rng = np.random.default_rng(21)
    docs = [from_plain(bench.make_template(rng, i)) for i in range(128)]
    texts = [
        bench.regex_heavy_rules(4).replace("rule rx_", f"rule g{i}_rx_")
        for i in range(8)
    ]
    rfs = [parse_rules_file(t, f"g{i}.guard") for i, t in enumerate(texts)]
    batch, interner = encode_batch(docs)
    compiled_files = [compile_rules_file(rf, interner) for rf in rfs]

    ev = PackShardedEvaluator(compiled_files, rule_shards=4)
    assert len(ev.shards) == 4  # 8 devices, 8 files -> 4 real groups
    per_file = [ShardedBatchEvaluator(c) for c in compiled_files]

    packed_st = ev(batch)  # compile
    serial_st = np.concatenate([pf(batch) for pf in per_file], axis=1)
    assert np.array_equal(packed_st, serial_st), "pack-sharded parity"

    reps = 3
    t0 = time.perf_counter()
    for _ in range(reps):
        ev(batch)
    t_packed = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(reps):
        for pf in per_file:
            pf(batch)
    t_serial = time.perf_counter() - t0
    print(
        f"packed-group sharding dryrun: {len(ev.shards)} groups, "
        f"packed {t_packed / reps * 1e3:.1f}ms/run vs serial "
        f"{t_serial / reps * 1e3:.1f}ms/run "
        f"(speedup {t_serial / max(t_packed, 1e-9):.2f}x)"
    )
