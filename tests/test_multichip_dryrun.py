"""The driver-contract dry run must be hermetic: it runs in a fresh
subprocess WITHOUT conftest.py's JAX_PLATFORMS=cpu forcing, on a host
whose default JAX backend may be a (possibly wedged) TPU tunnel. The
dry run must pick the virtual CPU mesh and never commit an array to
the default device (VERDICT round 1, item 1)."""

import os
import pathlib
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent


# platform=None: default platform untouched (may resolve to a TPU
# backend). platform="cpu": the env var is set but NOT honored on hosts
# whose TPU plugin self-registers (axon) — the dry run must force the
# platform programmatically either way.
@pytest.mark.parametrize("platform", [None, "cpu"])
def test_dryrun_multichip_subprocess_no_platform_forcing(platform):
    env = os.environ.copy()
    env.pop("JAX_PLATFORMS", None)
    if platform is not None:
        env["JAX_PLATFORMS"] = platform
    flags = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    env["XLA_FLAGS"] = " ".join(
        flags + ["--xla_force_host_platform_device_count=8"]
    )
    proc = subprocess.run(
        [
            sys.executable,
            "-c",
            "from __graft_entry__ import dryrun_multichip; dryrun_multichip(8)",
        ],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=420,
    )
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert "dryrun_multichip OK" in proc.stdout


def test_dryrun_multichip_in_process():
    # under conftest's 8-device CPU mesh this must also just work
    sys.path.insert(0, str(REPO))
    from __graft_entry__ import dryrun_multichip

    dryrun_multichip(8)
