"""The driver-contract dry run must be hermetic: it runs in a fresh
subprocess WITHOUT conftest.py's JAX_PLATFORMS=cpu forcing, on a host
whose default JAX backend may be a (possibly wedged) TPU tunnel. The
dry run must pick the virtual CPU mesh and never commit an array to
the default device (VERDICT round 1, item 1)."""

import os
import pathlib
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent


# platform=None: default platform untouched (may resolve to a TPU
# backend). platform="cpu": the env var is set but NOT honored on hosts
# whose TPU plugin self-registers (axon). platform="axon": the ambient
# environment names a TPU plugin outright — the real driver host does
# exactly this — and the dry run must still force CPU programmatically.
# with_flag=False: XLA_FLAGS carries no device-count flag at all; the
# dry run must inject it itself before backend init.
@pytest.mark.parametrize(
    "platform,with_flag",
    [(None, True), ("cpu", True), ("axon", True), (None, False), ("axon", False)],
)
def test_dryrun_multichip_subprocess_no_platform_forcing(platform, with_flag):
    env = os.environ.copy()
    env.pop("JAX_PLATFORMS", None)
    if platform is not None:
        env["JAX_PLATFORMS"] = platform
    flags = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    if with_flag:
        flags.append("--xla_force_host_platform_device_count=8")
    env["XLA_FLAGS"] = " ".join(flags)
    proc = subprocess.run(
        [
            sys.executable,
            "-c",
            "from __graft_entry__ import dryrun_multichip; dryrun_multichip(8)",
        ],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=420,
    )
    assert proc.returncode == 0, proc.stderr[-4000:]
    # the platform in the OK line proves the run was hermetic: a
    # regression to real TPU devices would also print "OK" on a
    # healthy multi-chip host, but not with cpu devices
    assert "dryrun_multichip OK: 8 cpu devices" in proc.stdout


def test_dryrun_multichip_in_process():
    # under conftest's 8-device CPU mesh this must also just work
    sys.path.insert(0, str(REPO))
    from __graft_entry__ import dryrun_multichip

    dryrun_multichip(8)
