"""Incremental validation plane suite (guard_tpu/cache/results.py):
cache-key sensitivity (doc bytes, rule content, guard_tpu version,
output config each flip the key; file names never do), entry
round-trips with the portable-name contract, corrupt / truncated /
mismatched entries degrading to logged misses, and the end-to-end
parity gates: warm-cache and --no-result-cache runs must be
byte-identical across output modes, worker counts, pack modes and
exit codes; quarantined and oracle-error docs never enter the cache;
mixed 50%-hit chunks interleave cached and fresh outcomes in document
order. The result cache buys dispatches, never bits."""

import json

import pytest

from guard_tpu.cache import results as rcache
from guard_tpu.cli import run
from guard_tpu.commands.validate import RuleFile
from guard_tpu.core.parser import parse_rules_file
from guard_tpu.ops import plan as plan_mod
from guard_tpu.utils.io import Reader, Writer

RULES_A = (
    "let b = Resources.*[ Type == 'AWS::S3::Bucket' ]\n"
    "rule sse when %b !empty { %b.Properties.Enc == true }\n"
)
RULES_B = (
    "rule named { Resources.*.Properties.Name in ['web', 'db'] }\n"
    "rule arnish { Resources.*.Properties.Arn == /^arn:aws:/ }\n"
)
# EMPTY on an int raises GuardError in the oracle: the doc's stderr
# line must re-emit on every run, so it can never be served from cache
RULES_ERR = "rule em { Resources.R1.Properties.X !empty }\n"


def _rule_file(content: str, name: str = "r.guard") -> RuleFile:
    return RuleFile(
        name=name, full_name=name, content=content,
        rules=parse_rules_file(content, name),
    )


@pytest.fixture(autouse=True)
def _fresh_result_cache(tmp_path, monkeypatch):
    """The suite-wide conftest defaults the layer OFF (content-keyed
    entries would cross-hit between tests sharing fixture docs); each
    test here opts in with a private store."""
    monkeypatch.setenv("GUARD_TPU_RESULT_CACHE", "1")
    monkeypatch.setenv(
        "GUARD_TPU_RESULT_CACHE_DIR", str(tmp_path / "results")
    )
    rcache.reset_result_cache_stats()
    yield
    rcache.reset_result_cache_stats()


def _mk_corpus(tmp_path, n=6, fail=(1, 4), extra_rules=(RULES_B,)):
    data = tmp_path / "data"
    data.mkdir(exist_ok=True)
    rule_paths = []
    for i, content in enumerate((RULES_A,) + tuple(extra_rules)):
        p = tmp_path / f"rules{i}.guard"
        p.write_text(content)
        rule_paths.append(str(p))
    for i in range(n):
        doc = {
            "Resources": {
                f"b{i}": {
                    "Type": "AWS::S3::Bucket",
                    "Properties": {
                        "Enc": i not in fail,
                        "Name": "web" if i % 2 else "worker",
                        "Arn": f"arn:aws:s3:::b{i}",
                    },
                }
            }
        }
        (data / f"t{i:02d}.json").write_text(json.dumps(doc))
    return rule_paths, data


# ------------------------------------------------------ cache key


def test_result_key_sensitive_to_every_field():
    base = rcache.result_key("plan0", "doc0", "cfg0")
    assert base == rcache.result_key("plan0", "doc0", "cfg0")
    assert base != rcache.result_key("plan1", "doc0", "cfg0")
    assert base != rcache.result_key("plan0", "doc1", "cfg0")
    assert base != rcache.result_key("plan0", "doc0", "cfg1")


def test_result_key_covers_schema_version(monkeypatch):
    base = rcache.result_key("p", "d", "c")
    monkeypatch.setattr(
        rcache, "RESULT_SCHEMA_VERSION", rcache.RESULT_SCHEMA_VERSION + 1
    )
    assert rcache.result_key("p", "d", "c") != base


def test_doc_digest_changes_with_one_byte():
    assert rcache.doc_digest('{"a": 1}') != rcache.doc_digest('{"a": 2}')
    # str content hashes its utf-8: same bytes, same digest
    assert rcache.doc_digest('{"a": 1}') == rcache.doc_digest(b'{"a": 1}')


def test_rule_content_flips_key_but_file_name_does_not():
    """Rule sensitivity rides the plan digest: one rule byte changes
    the result key; renaming the rules file never does."""
    doc, cfg = rcache.doc_digest("{}"), rcache.config_hash(mode="sweep")
    base = rcache.result_key(
        plan_mod.plan_digest([_rule_file(RULES_A)]), doc, cfg
    )
    tweaked = rcache.result_key(
        plan_mod.plan_digest(
            [_rule_file(RULES_A.replace("true", "false"))]
        ),
        doc, cfg,
    )
    renamed = rcache.result_key(
        plan_mod.plan_digest([_rule_file(RULES_A, name="other.guard")]),
        doc, cfg,
    )
    assert base != tweaked
    assert base == renamed


def test_config_hash_field_order_independent_value_sensitive():
    a = rcache.config_hash(mode="validate", fmt="json", verbose=False)
    b = rcache.config_hash(verbose=False, fmt="json", mode="validate")
    c = rcache.config_hash(mode="validate", fmt="yaml", verbose=False)
    assert a == b
    assert a != c


# ------------------------------------------------- entry round trips


def test_store_load_roundtrip_and_counters():
    key = rcache.result_key("p", "d", "c")
    assert rcache.load_entry(key) is None  # absent file: plain miss
    assert rcache.store_entry(key, {"name": "t.json", "sweep": {}})
    payload = rcache.load_entry(key)
    assert payload == {"name": "t.json", "sweep": {}}
    stats = rcache.result_cache_stats()
    assert stats["hits"] == 1 and stats["misses"] == 1
    assert stats["stores"] == 1 and stats["corrupt_entries"] == 0
    assert stats["bytes_stored"] > 0 and stats["bytes_loaded"] > 0


def test_name_mismatch_is_plain_miss_unless_portable():
    key = rcache.result_key("p", "d", "c")
    rcache.store_entry(key, {"name": "a.json", "files": []})
    assert rcache.load_entry(key, name="b.json") is None
    stats = rcache.result_cache_stats()
    assert stats["misses"] == 1 and stats["corrupt_entries"] == 0
    # a portable entry replays under any name (the reader substitutes
    # its own into the report's top-level name field)
    key2 = rcache.result_key("p", "d2", "c")
    rcache.store_entry(key2, {"name": "a.json", "files": [],
                              "portable": True})
    assert rcache.load_entry(key2, name="b.json") is not None


def test_guard_version_mismatch_is_logged_miss(monkeypatch, caplog):
    key = rcache.result_key("p", "d", "c")
    rcache.store_entry(key, {"name": "t.json", "sweep": {}})
    monkeypatch.setattr(rcache, "_guard_version", lambda: "0.0.0-other")
    with caplog.at_level("WARNING", logger="guard_tpu.result_cache"):
        assert rcache.load_entry(key) is None
    stats = rcache.result_cache_stats()
    assert stats["corrupt_entries"] == 1
    assert any("version mismatch" in r.message for r in caplog.records)


@pytest.mark.parametrize("corruption", [
    b"\x00 torn write, not json",
    b'{"schema": 999, "payload": {}}',
    b'{"schema": 1, "version": "x", "key": "wrong", "payload": {}}',
    b'["not", "an", "object"]',
    b"",
])
def test_corrupt_entries_are_logged_misses(corruption, caplog):
    key = rcache.result_key("p", "d", "c")
    rcache.store_entry(key, {"name": "t.json", "sweep": {}})
    path = rcache.result_cache_dir() / f"{key}.result.json"
    path.write_bytes(corruption)
    with caplog.at_level("WARNING", logger="guard_tpu.result_cache"):
        assert rcache.load_entry(key) is None
    stats = rcache.result_cache_stats()
    assert stats["misses"] == 1 and stats["corrupt_entries"] == 1
    assert any("treating as a cache miss" in r.message
               for r in caplog.records)


def test_truncated_entry_degrades_to_recompute(caplog):
    key = rcache.result_key("p", "d", "c")
    rcache.store_entry(key, {"name": "t.json", "sweep": {"status": "pass"}})
    path = rcache.result_cache_dir() / f"{key}.result.json"
    path.write_bytes(path.read_bytes()[:20])
    with caplog.at_level("WARNING", logger="guard_tpu.result_cache"):
        assert rcache.load_entry(key) is None
    # the recompute's store rewrites the entry in place
    rcache.store_entry(key, {"name": "t.json", "sweep": {"status": "pass"}})
    assert rcache.load_entry(key) is not None


def test_unwritable_cache_dir_warns_and_continues(tmp_path, monkeypatch,
                                                 caplog):
    blocker = tmp_path / "blocked"
    blocker.write_text("a file where the cache dir should be")
    monkeypatch.setenv("GUARD_TPU_RESULT_CACHE_DIR", str(blocker))
    with caplog.at_level("WARNING", logger="guard_tpu.result_cache"):
        assert rcache.store_entry("k" * 64, {"name": "t"}) is False
    assert rcache.result_cache_stats()["stores"] == 0
    assert any("store failed" in r.message for r in caplog.records)


# ------------------------------------------------------- parity gates


def _sweep(rule_paths, data, tmp_path, tag, *extra):
    w = Writer.buffered()
    rc = run(
        ["sweep", "-r", *rule_paths, "-d", str(data),
         "-M", str(tmp_path / f"m-{tag}.jsonl"), "-c", "4",
         "--backend", "tpu", *extra],
        writer=w, reader=Reader(),
    )
    summary = json.loads(w.out.getvalue())
    summary.pop("manifest", None)  # the only path-bearing key
    manifest = (tmp_path / f"m-{tag}.jsonl").read_text()
    return rc, summary, w.err.getvalue(), manifest


@pytest.mark.parametrize("workers", [0, 2])
@pytest.mark.parametrize("pack", [(), ("--no-pack",)])
def test_sweep_parity_cached_vs_off(tmp_path, workers, pack):
    """Cold, all-hits warm and --no-result-cache sweeps are identical
    in exit code (19: failures present), summary, stderr and manifest
    rows — per-file and packed, with and without ingest workers."""
    rule_paths, data = _mk_corpus(tmp_path, n=8, fail=(2, 5))
    common = ("--ingest-workers", str(workers), *pack)
    cold = _sweep(rule_paths, data, tmp_path, f"c{workers}", *common)
    rcache.reset_result_cache_stats()
    warm = _sweep(rule_paths, data, tmp_path, f"w{workers}", *common)
    stats = rcache.result_cache_stats()
    assert stats["hits"] == 8 and stats["misses"] == 0
    off = _sweep(
        rule_paths, data, tmp_path, f"o{workers}", *common,
        "--no-result-cache",
    )
    assert cold[0] == 19
    assert cold == warm == off


def _validate(rule_paths, data, *extra):
    w = Writer.buffered()
    rc = run(
        ["validate", "-r", *rule_paths, "-d", str(data),
         "--backend", "tpu", *extra],
        writer=w, reader=Reader(),
    )
    return rc, w.out.getvalue(), w.err.getvalue()


@pytest.mark.parametrize(
    "fmt", ["single-line-summary", "json", "yaml", "junit"]
)
@pytest.mark.parametrize("workers", [0, 2])
def test_validate_output_modes_parity(tmp_path, fmt, workers):
    """Warm-cache validate replays byte-identical console / yaml /
    structured / junit output (exit 19: failures present)."""
    rule_paths, data = _mk_corpus(tmp_path, n=6, fail=(1, 4))
    extra = ("-o", fmt, "--ingest-workers", str(workers)) + (
        ("--structured", "--show-summary", "none")
        if fmt in ("json", "yaml", "junit") else ()
    )
    cold = _validate(rule_paths, data, *extra)
    rcache.reset_result_cache_stats()
    warm = _validate(rule_paths, data, *extra)
    stats = rcache.result_cache_stats()
    assert stats["hits"] == 6 and stats["misses"] == 0
    off = _validate(rule_paths, data, *extra, "--no-result-cache")
    assert cold[0] == 19
    assert cold == warm == off


def test_validate_perfile_parity(tmp_path):
    rule_paths, data = _mk_corpus(tmp_path, n=6, fail=(3,))
    cold = _validate(rule_paths, data, "--no-pack")
    warm = _validate(rule_paths, data, "--no-pack")
    off = _validate(rule_paths, data, "--no-pack", "--no-result-cache")
    assert cold == warm == off


def test_output_config_partitions_the_key(tmp_path):
    """A yaml-mode entry must never serve a json-mode request: the
    second format's first run is all misses, not poisoned hits."""
    rule_paths, data = _mk_corpus(tmp_path, n=4, fail=())
    structured = ("--structured", "--show-summary", "none")
    _validate(rule_paths, data, "-o", "json", *structured)
    rcache.reset_result_cache_stats()
    out = _validate(rule_paths, data, "-o", "yaml", *structured)
    stats = rcache.result_cache_stats()
    assert stats["hits"] == 0 and stats["misses"] == 4
    # and the yaml entries now exist independently
    rcache.reset_result_cache_stats()
    again = _validate(rule_paths, data, "-o", "yaml", *structured)
    assert rcache.result_cache_stats()["hits"] == 4
    assert out == again


def test_doc_edit_invalidates_only_that_doc(tmp_path):
    """Structural invalidation: rewriting one doc's bytes re-dispatches
    exactly that doc; the rest replay. Byte parity holds throughout."""
    rule_paths, data = _mk_corpus(tmp_path, n=6, fail=(1,))
    _sweep(rule_paths, data, tmp_path, "seed")
    doc = json.loads((data / "t03.json").read_text())
    doc["Touched"] = True
    (data / "t03.json").write_text(json.dumps(doc))
    rcache.reset_result_cache_stats()
    touched = _sweep(rule_paths, data, tmp_path, "touched")
    stats = rcache.result_cache_stats()
    assert stats["hits"] == 5 and stats["misses"] == 1
    assert stats["stores"] == 1
    off = _sweep(
        rule_paths, data, tmp_path, "touched-off", "--no-result-cache"
    )
    assert touched == off


def test_delta_stats_flag_reports_partition(tmp_path):
    rule_paths, data = _mk_corpus(tmp_path, n=4, fail=())
    _sweep(rule_paths, data, tmp_path, "seed")
    out = _sweep(rule_paths, data, tmp_path, "warm", "--delta-stats")
    assert "result-cache: 4/4 docs cached, 0 dispatched" in out[2]


# --------------------------------------------- never-cached outcomes


def _stored_doc_names():
    return {
        json.loads(p.read_text()).get("payload", {}).get("name")
        for p in rcache.result_cache_dir().glob("*.result.json")
    }


def test_quarantined_docs_never_cached(tmp_path):
    """An unparseable doc re-evaluates (and re-reports its quarantine
    record) on every run; it never enters the store — and neither does
    any chunk whose snapshot saw the failure plane move (the guard is
    conservative across pipelined in-flight chunks). Output parity
    holds across runs."""
    rule_paths, data = _mk_corpus(tmp_path, n=4, fail=())
    (data / "poison.json").write_text("{ not json")
    first = _sweep(rule_paths, data, tmp_path, "q1")
    assert first[1]["quarantined"][0]["file"] == "poison.json"
    stats = rcache.result_cache_stats()
    assert 0 < stats["stores"] < 5
    assert "poison.json" not in _stored_doc_names()
    rcache.reset_result_cache_stats()
    second = _sweep(rule_paths, data, tmp_path, "q2")
    stats = rcache.result_cache_stats()
    # the poisoned doc re-misses every run; clean stored docs replay
    assert stats["misses"] >= 1
    assert stats["hits"] + stats["misses"] == 5
    assert "poison.json" not in _stored_doc_names()
    assert first == second


def test_oracle_error_docs_never_cached(tmp_path):
    """A doc whose oracle pass raises GuardError (EMPTY on an int)
    re-emits its stderr line on every run — it is uncacheable by
    design. Clean docs in the same chunk still cache."""
    data = tmp_path / "data"
    data.mkdir()
    rules = tmp_path / "err.guard"
    rules.write_text(RULES_ERR)
    (data / "bad.json").write_text(
        json.dumps({"Resources": {"R1": {"Properties": {"X": 5}}}})
    )
    (data / "good.json").write_text(
        json.dumps({"Resources": {"R1": {"Properties": {"X": []}}}})
    )
    first = _sweep([str(rules)], data, tmp_path, "e1")
    assert first[0] == 5  # oracle error: exit ERROR
    assert "bad.json" in first[2]
    stats = rcache.result_cache_stats()
    assert stats["stores"] == 1  # only good.json stored
    rcache.reset_result_cache_stats()
    second = _sweep([str(rules)], data, tmp_path, "e2")
    stats = rcache.result_cache_stats()
    assert stats["hits"] == 1 and stats["misses"] == 1
    assert first == second  # the error line re-emitted identically


def test_faulted_chunks_never_cached(tmp_path, monkeypatch):
    """A chunk during which the failure plane moved (injected dispatch
    fault -> oracle fallback) must not write back ANY of its docs."""
    rule_paths, data = _mk_corpus(tmp_path, n=4, fail=())
    monkeypatch.setenv("GUARD_TPU_FAULT", "dispatch:nth=1")
    _sweep(rule_paths, data, tmp_path, "faulted")
    assert rcache.result_cache_stats()["stores"] == 0
    monkeypatch.delenv("GUARD_TPU_FAULT")
    # the clean re-run recomputes (no poisoned entries to replay) and
    # only then populates the store
    rcache.reset_result_cache_stats()
    _sweep(rule_paths, data, tmp_path, "clean")
    stats = rcache.result_cache_stats()
    assert stats["hits"] == 0 and stats["stores"] == 4


# --------------------------------------------------- mixed-hit chunks


def test_mixed_hit_chunk_interleaves_in_document_order(tmp_path):
    """A chunk where every second doc is cached folds cached and fresh
    outcomes back in ORIGINAL document order: summary tallies, failed
    list and manifest rows are byte-identical to the cache-off run."""
    rule_paths, data = _mk_corpus(tmp_path, n=8, fail=(1, 2, 6))
    # seed the store with the EVEN docs only
    seed_dir = tmp_path / "seed_data"
    seed_dir.mkdir()
    for p in sorted(data.glob("t*.json")):
        if int(p.stem[1:]) % 2 == 0:
            (seed_dir / p.name).write_text(p.read_text())
    _sweep(rule_paths, seed_dir, tmp_path, "seed", "-c", "8")
    # full corpus in ONE chunk: 50% hits, 50% fresh, interleaved
    rcache.reset_result_cache_stats()
    mixed = _sweep(rule_paths, data, tmp_path, "mixed", "-c", "8")
    stats = rcache.result_cache_stats()
    assert stats["hits"] == 4 and stats["misses"] == 4
    off = _sweep(
        rule_paths, data, tmp_path, "mixed-off", "-c", "8",
        "--no-result-cache",
    )
    assert mixed == off
    # the failed list preserved document order across the seam
    fails = [f["data"] for f in mixed[1]["failed"]]
    assert fails == sorted(fails)


def test_corrupt_store_degrades_to_recompute_e2e(tmp_path, caplog):
    """Corrupting every entry between runs degrades to logged misses
    and a recompute whose output stays byte-identical."""
    rule_paths, data = _mk_corpus(tmp_path, n=4, fail=(0,))
    first = _sweep(rule_paths, data, tmp_path, "pre")
    for ent in rcache.result_cache_dir().glob("*.result.json"):
        ent.write_bytes(b"{ torn write")
    rcache.reset_result_cache_stats()
    with caplog.at_level("WARNING", logger="guard_tpu.result_cache"):
        second = _sweep(rule_paths, data, tmp_path, "post")
    stats = rcache.result_cache_stats()
    assert stats["corrupt_entries"] == 4 and stats["hits"] == 0
    assert first == second
    # the recompute rewrote the entries: third run is all hits
    rcache.reset_result_cache_stats()
    third = _sweep(rule_paths, data, tmp_path, "rewrite")
    assert rcache.result_cache_stats()["hits"] == 4
    assert first == third


# ------------------------------------------------------ escape hatches


def test_env_escape_hatch_disables_layer(tmp_path, monkeypatch):
    rule_paths, data = _mk_corpus(tmp_path, n=4, fail=(0,))
    monkeypatch.setenv("GUARD_TPU_RESULT_CACHE", "0")
    off = _sweep(rule_paths, data, tmp_path, "env-off")
    stats = rcache.result_cache_stats()
    assert stats["hits"] == stats["misses"] == stats["stores"] == 0
    assert not list(rcache.result_cache_dir().glob("*.result.json"))
    monkeypatch.setenv("GUARD_TPU_RESULT_CACHE", "1")
    on = _sweep(rule_paths, data, tmp_path, "env-on")
    assert off == on


def test_flag_escape_hatch_never_reads_or_writes(tmp_path):
    rule_paths, data = _mk_corpus(tmp_path, n=4, fail=())
    _sweep(rule_paths, data, tmp_path, "seed")
    rcache.reset_result_cache_stats()
    _sweep(rule_paths, data, tmp_path, "off", "--no-result-cache")
    stats = rcache.result_cache_stats()
    assert stats["hits"] == stats["misses"] == stats["stores"] == 0
