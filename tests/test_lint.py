"""Static-analysis plane: Guard rule linter suite
(guard_tpu/analysis/lint.py + the `guard-tpu lint` subcommand).

One hand-built fixture per check proves each code fires where it
should; the adversarial `ok.guard` fixture (bounded intervals,
`some`-quantified contradictions, referenced variables) proves the
conservative analysis stays silent where it must — the zero-false-
positive bar the shipped corpora pin in test_lint_corpus.py. The CLI
half pins the documented exit-code contract: 0 clean, 19 findings at
or above --fail-on, 5 parse error (which takes precedence).
"""

import json

import pytest

from guard_tpu.analysis.lint import (
    CHECKS,
    lint_files,
    max_severity,
)
from guard_tpu.cli import run
from guard_tpu.core.parser import parse_rules_file
from guard_tpu.utils.io import Reader, Writer

# -------------------------------------------------------- fixtures
# one per check; names match the lint code they provoke

FIXTURES = {
    # > 5 AND < 3 on one path: the interval is empty
    "unsat.guard": (
        "rule unsat_rule {\n"
        "    Resources.*.Properties.Count > 5\n"
        "    Resources.*.Properties.Count < 3\n"
        "}\n"
    ),
    # two different string equalities on one path
    "unsat_str.guard": (
        "rule unsat_str_rule {\n"
        "    Resources.*.Type == 'AWS::S3::Bucket'\n"
        "    Resources.*.Type == 'AWS::EC2::Instance'\n"
        "}\n"
    ),
    # IS_STRING and IS_LIST cannot both hold
    "typeconf.guard": (
        "rule type_conflict_rule {\n"
        "    Resources.*.Properties.Tags is_string\n"
        "    Resources.*.Properties.Tags is_list\n"
        "}\n"
    ),
    # the when guard itself is unsatisfiable: the body never runs
    "deadwhen.guard": (
        "rule dead_when_rule when Parameters.Env == 'prod'\n"
        "                         Parameters.Env == 'dev' {\n"
        "    Resources.*.Properties.Enc == true\n"
        "}\n"
    ),
    # nested when-block inside the body, same contradiction
    "deadwhen2.guard": (
        "rule dead_inner_when_rule {\n"
        "    when Parameters.Count >= 10\n"
        "         Parameters.Count <= 2 {\n"
        "        Resources.*.Properties.Enc == true\n"
        "    }\n"
        "}\n"
    ),
    # filter predicate selects the empty set
    "unsatfilter.guard": (
        "rule unsat_filter_rule {\n"
        "    Resources.*[ Properties.Port > 100\n"
        "                 Properties.Port < 50 ].Type == 'X'\n"
        "}\n"
    ),
    # same name, different bodies: later definition shadows
    "shadow.guard": (
        "rule twice { Resources.*.Properties.A == 1 }\n"
        "rule twice { Resources.*.Properties.B == 2 }\n"
    ),
    # same name, byte-identical bodies (modulo location): duplicate
    "dup.guard": (
        "rule copied { Resources.*.Properties.A == 1 }\n"
        "rule copied { Resources.*.Properties.A == 1 }\n"
    ),
    # %unused is assigned, never referenced
    "deadlet.guard": (
        "let unused = ['a', 'b']\n"
        "rule uses_nothing { Resources.*.Properties.C == 3 }\n"
    ),
    # adversarial CLEAN file: bounded interval, some-quantified
    # "contradiction" (each element may satisfy a different branch),
    # and a variable that IS referenced
    "ok.guard": (
        "let allowed = ['web', 'db']\n"
        "rule ok_rule {\n"
        "    Resources.*.Properties.Name in %allowed\n"
        "    Resources.*.Properties.Count >= 3\n"
        "    Resources.*.Properties.Count <= 5\n"
        "    some Resources.*.Properties.Kind == 'a'\n"
        "    some Resources.*.Properties.Kind == 'b'\n"
        "}\n"
    ),
}

EXPECT = {
    "unsat.guard": ("unsat-conjunction", "ERROR"),
    "unsat_str.guard": ("unsat-conjunction", "ERROR"),
    "typeconf.guard": ("type-conflict", "ERROR"),
    "deadwhen.guard": ("always-skip-when", "WARNING"),
    "deadwhen2.guard": ("always-skip-when", "WARNING"),
    "unsatfilter.guard": ("unsat-filter", "WARNING"),
    "shadow.guard": ("shadowed-rule", "WARNING"),
    "dup.guard": ("duplicate-rule", "WARNING"),
    "deadlet.guard": ("unreferenced-variable", "WARNING"),
}


def _lint_one(name):
    rf = parse_rules_file(FIXTURES[name], name)
    return lint_files([(name, rf)])


@pytest.mark.parametrize("name", sorted(EXPECT))
def test_each_check_fires(name):
    code, severity = EXPECT[name]
    findings = _lint_one(name)
    assert findings, f"{name} must produce at least one finding"
    hits = [f for f in findings if f.code == code]
    assert hits, f"{name}: expected {code}, got {[f.code for f in findings]}"
    assert hits[0].severity == severity
    assert hits[0].file == name
    # every rule-scoped finding names its rule (file-scope `let`
    # findings legitimately have no rule to name)
    if code != "unreferenced-variable":
        assert hits[0].rule


def test_clean_fixture_is_silent():
    assert _lint_one("ok.guard") == []


def test_findings_carry_locations_and_render():
    f = _lint_one("unsat.guard")[0]
    assert f.line > 0
    text = f.render()
    assert text.startswith("unsat.guard:")
    assert "[unsat-conjunction]" in text and "ERROR" in text
    doc = f.to_json()
    assert doc["code"] == "unsat-conjunction" and doc["line"] == f.line


def test_every_emitted_code_is_catalogued():
    parsed = [(n, parse_rules_file(c, n)) for n, c in FIXTURES.items()]
    for f in lint_files(parsed):
        assert f.code in CHECKS
    assert max_severity([]) is None
    assert max_severity(lint_files(parsed)) == "ERROR"


def test_cross_file_duplicate_is_info():
    parsed = [
        (n, parse_rules_file("rule same_name { Resources.*.P == 1 }\n", n))
        for n in ("one.guard", "two.guard")
    ]
    findings = lint_files(parsed)
    assert [f.code for f in findings] == ["cross-file-duplicate",
                                          "cross-file-duplicate"] or [
        f.code for f in findings] == ["cross-file-duplicate"]
    assert all(f.severity == "INFO" for f in findings)


# ------------------------------------------------------ CLI contract


def _write_fixtures(tmp_path, names):
    for n in names:
        (tmp_path / n).write_text(FIXTURES[n])


def _run_lint(tmp_path, *extra):
    w = Writer.buffered()
    rc = run(["lint", "-r", str(tmp_path), *extra], writer=w,
             reader=Reader())
    return rc, w.out.getvalue(), w.err.getvalue()


def test_cli_exit_0_on_clean(tmp_path):
    _write_fixtures(tmp_path, ["ok.guard"])
    rc, out, err = _run_lint(tmp_path)
    assert rc == 0 and out == ""
    assert "0 error(s)" in err


def test_cli_exit_19_on_error_findings(tmp_path):
    _write_fixtures(tmp_path, ["ok.guard", "unsat.guard"])
    rc, out, _err = _run_lint(tmp_path)
    assert rc == 19
    assert "[unsat-conjunction]" in out


def test_cli_fail_on_threshold(tmp_path):
    _write_fixtures(tmp_path, ["shadow.guard"])  # WARNING only
    assert _run_lint(tmp_path)[0] == 0  # default --fail-on error
    assert _run_lint(tmp_path, "--fail-on", "warning")[0] == 19
    assert _run_lint(tmp_path, "--fail-on", "never")[0] == 0


def test_cli_exit_5_on_parse_error_takes_precedence(tmp_path):
    _write_fixtures(tmp_path, ["unsat.guard"])
    (tmp_path / "broken.guard").write_text("rule broken {\n  this is not(((\n")
    rc, out, err = _run_lint(tmp_path)
    assert rc == 5
    assert "Parse Error" in err
    # the parseable file was still linted
    assert "[unsat-conjunction]" in out


def test_cli_structured_json(tmp_path):
    _write_fixtures(tmp_path, ["unsat.guard", "shadow.guard"])
    rc, out, _err = _run_lint(tmp_path, "--structured", "--fail-on",
                              "never")
    assert rc == 0
    doc = json.loads(out)
    codes = {f["code"] for f in doc["findings"]}
    assert {"unsat-conjunction", "shadowed-rule"} <= codes
    assert doc["summary"]["files"] == 2
    assert doc["summary"]["error"] == 1
