"""Concurrent-parity suite for the serving plane (guard_tpu/serve/).

The coalescing batcher's one correctness contract: N threads replaying
a request mix against a concurrent session must produce BYTE-IDENTICAL
per-request responses (code, output, error) to N sequential
`serve --stdio` runs of the same mix — across packed/per-file dispatch
and ingest-worker settings, including a poisoned request per batch
(which must drop to the solo path without failing its batch peers).
On top of parity: 16 concurrent same-rules requests must produce
several-fold fewer device dispatches than sequential serve, the stdio
session must multiplex `"id"`-tagged requests, and the TCP/HTTP
listener must answer the same envelopes over sockets.
"""

import json
import socket
import threading
import time

import pytest

from guard_tpu.commands.serve import Serve
from guard_tpu.utils import telemetry
from guard_tpu.utils.io import Reader, Writer

RULES = [
    "rule has_a { a exists }\nrule b_is_one { b == 1 }",
    "rule c_small { c < 10 }",
]


def _req(i, poisoned=False, rules=None, **extra):
    data = [
        json.dumps({"a": i, "b": 1, "c": i % 7}),
        json.dumps({"a": i + 1, "b": 1, "c": 3}),
    ]
    if poisoned:
        data[0] = '{"a": '  # truncated JSON: load_document raises
    body = {
        "rules": RULES if rules is None else rules,
        "data": data,
        "backend": "tpu",
        **extra,
    }
    return json.dumps(body)


def _envelope(resp):
    return (resp["code"], resp.get("output"), resp.get("error"),
            resp.get("error_class"))


def _sequential(monkeypatch, lines):
    """The baseline: one request at a time, coalescing off — exactly
    the original single-client session."""
    monkeypatch.setenv("GUARD_TPU_COALESCE", "0")
    srv = Serve(stdio=True)
    out = [_envelope(srv.handle_line(ln)) for ln in lines]
    monkeypatch.setenv("GUARD_TPU_COALESCE", "1")
    return out


def _concurrent(lines, wait_ms="150"):
    """N threads against one coalescing session."""
    srv = Serve(stdio=True, coalesce=True)
    results = [None] * len(lines)
    barrier = threading.Barrier(len(lines))

    def worker(i):
        barrier.wait()
        results[i] = _envelope(srv.handle_line(lines[i]))

    threads = [
        threading.Thread(target=worker, args=(i,))
        for i in range(len(lines))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return results


@pytest.mark.parametrize("pack", ["0", "1"])
@pytest.mark.parametrize("workers", ["0", "2"])
def test_concurrent_parity_with_poisoned_peer(monkeypatch, pack, workers):
    """Byte parity across dispatch modes, with one poisoned request in
    the mix: its error envelope reproduces exactly and its batch peers
    still answer correctly."""
    monkeypatch.setenv("GUARD_TPU_PACK", pack)
    monkeypatch.setenv("GUARD_TPU_INGEST_WORKERS", workers)
    monkeypatch.setenv("GUARD_TPU_COALESCE_WAIT_MS", "150")
    lines = [_req(i, poisoned=(i == 3)) for i in range(8)]
    seq = _sequential(monkeypatch, lines)
    con = _concurrent(lines)
    assert con == seq
    assert seq[3][0] == 5  # the poisoned request errored in BOTH runs
    assert seq[3][3] == "ParseError"
    ok = [i for i in range(8) if i != 3]
    assert all(seq[i][0] == 0 for i in ok)


@pytest.mark.parametrize("out_fmt", ["sarif", "json"])
def test_concurrent_parity_output_formats(monkeypatch, out_fmt):
    monkeypatch.setenv("GUARD_TPU_COALESCE_WAIT_MS", "150")
    lines = [_req(i, output_format=out_fmt) for i in range(6)]
    assert _concurrent(lines) == _sequential(monkeypatch, lines)


def test_concurrent_mixed_digests_group_separately(monkeypatch):
    """Two distinct rule registries in flight: each coalesces with its
    own digest group, responses stay per-request correct."""
    monkeypatch.setenv("GUARD_TPU_COALESCE_WAIT_MS", "150")
    alt = ["rule alt { z exists }"]
    lines = [
        _req(i, rules=(alt if i % 2 else None)) for i in range(8)
    ]
    assert _concurrent(lines) == _sequential(monkeypatch, lines)


def test_coalescing_reduces_dispatches(monkeypatch):
    """The acceptance gate: 16 concurrent requests against one rule
    digest must coalesce into >= 4x fewer device dispatches than the
    sequential baseline, with byte-identical responses, visible in the
    serve counters."""
    from guard_tpu.parallel.mesh import DISPATCH_COUNTERS
    from guard_tpu.utils.telemetry import SERVE_COUNTERS

    monkeypatch.setenv("GUARD_TPU_COALESCE_WAIT_MS", "300")
    lines = [_req(i) for i in range(16)]

    telemetry.REGISTRY.reset()
    seq = _sequential(monkeypatch, lines)
    seq_dispatches = DISPATCH_COUNTERS["dispatches"]

    telemetry.REGISTRY.reset()
    con = _concurrent(lines)
    con_dispatches = DISPATCH_COUNTERS["dispatches"]

    assert con == seq
    assert seq_dispatches >= 16
    assert con_dispatches * 4 <= seq_dispatches
    assert SERVE_COUNTERS["coalesced_batches"] >= 1
    assert SERVE_COUNTERS["coalesced_requests"] >= 2


def test_adaptive_window_skips_for_lone_request(monkeypatch):
    """A request admitted to an EMPTY queue dispatches immediately —
    the formation wait is skipped and counted, so an unloaded session
    (concurrency 1) does not pay the coalesce window as pure latency.
    The answer stays byte-identical to the sequential baseline."""
    from guard_tpu.utils.telemetry import SERVE_COUNTERS

    monkeypatch.setenv("GUARD_TPU_COALESCE_WAIT_MS", "300")
    lines = [_req(0)]
    seq = _sequential(monkeypatch, lines)
    telemetry.REGISTRY.reset()
    srv = Serve(stdio=True, coalesce=True)
    t0 = time.monotonic()
    got = [_envelope(srv.handle_line(lines[0]))]
    elapsed = time.monotonic() - t0
    assert got == seq
    assert SERVE_COUNTERS["coalesce_window_adaptive"] >= 1
    # far under the 300ms window it would otherwise have waited out
    assert elapsed < 0.25


def test_injected_serve_batch_fault_refires_solo(monkeypatch):
    """The failure plane's serving leg: an injected serve_batch fault
    quarantines the BATCH — every member re-fires through the solo
    path and still answers byte-identically to sequential."""
    from guard_tpu.utils import faults
    from guard_tpu.utils.telemetry import SERVE_COUNTERS

    monkeypatch.setenv("GUARD_TPU_COALESCE_WAIT_MS", "150")
    lines = [_req(i) for i in range(4)]
    seq = _sequential(monkeypatch, lines)

    faults.reset_faults()
    monkeypatch.setenv("GUARD_TPU_FAULT", "serve_batch:nth=1")
    telemetry.REGISTRY.reset()
    try:
        con = _concurrent(lines)
    finally:
        monkeypatch.delenv("GUARD_TPU_FAULT")
        refires = SERVE_COUNTERS["isolation_refires"]
        injected = faults.FAULT_COUNTERS["injected_serve_batch"]
        faults.reset_faults()
    assert con == seq
    assert injected == 1
    assert refires >= 1


def test_stdio_session_multiplexes_tagged_requests(monkeypatch):
    """`"id"`-tagged requests over one stdio session: every response
    carries its request's id and matches the sequential envelope."""
    monkeypatch.setenv("GUARD_TPU_COALESCE_WAIT_MS", "100")
    lines = [_req(i, id=f"r{i}") for i in range(6)]
    seq = _sequential(monkeypatch, [_req(i) for i in range(6)])

    w = Writer.buffered()
    rc = Serve(stdio=True).execute(
        w, Reader.from_string("\n".join(lines) + "\n")
    )
    assert rc == 0
    resps = {r["id"]: r for r in
             (json.loads(l) for l in w.out.getvalue().splitlines() if l)}
    assert set(resps) == {f"r{i}" for i in range(6)}
    for i in range(6):
        assert _envelope(resps[f"r{i}"]) == seq[i]


def test_untagged_stdio_session_stays_in_order(monkeypatch):
    """Untagged requests keep the original strictly-ordered protocol."""
    lines = [_req(i) for i in range(3)]
    seq = _sequential(monkeypatch, lines)
    w = Writer.buffered()
    rc = Serve(stdio=True).execute(
        w, Reader.from_string("\n".join(lines) + "\n")
    )
    assert rc == 0
    got = [json.loads(l) for l in w.out.getvalue().splitlines() if l]
    assert [_envelope(r) for r in got] == seq
    assert all("id" not in r for r in got)


def _recv_lines(sock_file, n):
    return [json.loads(sock_file.readline()) for _ in range(n)]


def test_tcp_listener_serves_jsonl_clients(monkeypatch):
    """Two TCP clients against one listener: same envelopes as a
    sequential stdio session, ids echoed."""
    from guard_tpu.serve.server import ServeServer

    monkeypatch.setenv("GUARD_TPU_COALESCE_WAIT_MS", "100")
    lines = [_req(i) for i in range(4)]
    seq = _sequential(monkeypatch, lines)

    srv = Serve(stdio=False, coalesce=True)
    server = ServeServer(srv, "127.0.0.1:0").start()
    try:
        results = {}

        def client(idx):
            with socket.create_connection(
                ("127.0.0.1", server.port), timeout=30
            ) as s:
                f = s.makefile("rwb")
                for i in range(idx, 4, 2):
                    tagged = json.loads(lines[i])
                    tagged["id"] = i
                    f.write((json.dumps(tagged) + "\n").encode())
                f.flush()
                s.shutdown(socket.SHUT_WR)
                for r in (json.loads(l) for l in f if l.strip()):
                    results[r["id"]] = r

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        server.stop()
    assert set(results) == {0, 1, 2, 3}
    for i in range(4):
        assert _envelope(results[i]) == seq[i]


def test_http_listener_answers_post_and_metrics(monkeypatch):
    """The curl-able face: POST /validate returns the response
    envelope, GET /metrics the live snapshot."""
    import http.client

    from guard_tpu.serve.server import ServeServer

    srv = Serve(stdio=False)
    server = ServeServer(srv, "127.0.0.1:0").start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                          timeout=30)
        conn.request("POST", "/validate", body=_req(1),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        body = json.loads(resp.read())
        assert resp.status == 200
        assert body["code"] == 0
        conn.close()

        conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                          timeout=30)
        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        snap = json.loads(resp.read())
        assert resp.status == 200
        assert snap["metrics"]["schema_version"] == telemetry.SCHEMA_VERSION
        conn.close()
    finally:
        server.stop()


def test_metrics_survive_concurrency_without_reset(monkeypatch):
    """Satellite: no per-request global reset — cumulative counters
    grow monotonically across concurrent requests and the metrics
    envelope carries a last_request diff."""
    monkeypatch.setenv("GUARD_TPU_COALESCE_WAIT_MS", "100")
    telemetry.REGISTRY.reset(include_persistent=True)
    srv = Serve(stdio=True, coalesce=True)
    lines = [_req(i) for i in range(6)]
    threads = [
        threading.Thread(target=srv.handle_line, args=(lines[i],))
        for i in range(6)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    m = srv.handle_line(json.dumps({"metrics": True}))
    snap = m["metrics"]
    assert snap["counters"]["serve"]["requests"] == 6
    assert snap["histograms"]["serve_request_seconds"]["count"] == 6
    assert isinstance(m["last_request"], dict)
    telemetry.REGISTRY.reset(include_persistent=True)


def test_abandoned_thread_cap(monkeypatch):
    """Satellite: past GUARD_TPU_SERVE_ABANDONED_MAX the session stops
    abandoning executors (no unbounded thread leak), keeps answering
    RequestTimeout, and the abandoned count rides the gauge."""
    import time

    from guard_tpu.commands import validate as validate_mod
    from guard_tpu.utils.telemetry import SERVE_COUNTERS

    def slow_execute(self, writer, reader):
        time.sleep(0.6)
        return 0

    monkeypatch.setattr(validate_mod.Validate, "execute", slow_execute)
    monkeypatch.setenv("GUARD_TPU_SERVE_TIMEOUT", "0.05")
    monkeypatch.setenv("GUARD_TPU_SERVE_ABANDONED_MAX", "1")
    telemetry.REGISTRY.reset()
    srv = Serve(stdio=True, coalesce=False)
    r1 = srv.handle_line(_req(0))
    assert r1["error_class"] == "RequestTimeout"
    assert srv._abandoned == 1
    r2 = srv.handle_line(_req(1))
    assert r2["error_class"] == "RequestTimeout"
    assert srv._abandoned == 1  # cap held: no second abandonment
    assert SERVE_COUNTERS["abandoned_threads"] == 1
    assert srv._abandoned_warned


def test_rules_cache_stays_bounded_with_gauge(monkeypatch):
    """Satellite: the prepared-rules cache evicts LRU past its ceiling
    and exports its size as a gauge."""
    from guard_tpu.commands.serve import _RULES_CACHE_MAX

    srv = Serve(stdio=True, coalesce=False)
    for i in range(_RULES_CACHE_MAX + 4):
        srv.handle_line(json.dumps({
            "rules": [f"rule r{i} {{ a exists }}"],
            "data": ['{"a": 1}'],
        }))
    assert len(srv._rules_cache) == _RULES_CACHE_MAX
    snap = telemetry.metrics_snapshot()
    assert snap["gauges"]["serve_rules_cache_size"] == _RULES_CACHE_MAX
