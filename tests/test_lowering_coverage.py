"""Differential tests for lowering coverage added in round 2
(VERDICT item 6): keys-ordering filters, literal and query variable
key interpolation, and map / nested-list struct literals as RHS.
Every case must lower (no host fallback) and match the CPU oracle."""

import numpy as np
import pytest

from guard_tpu.core.parser import parse_rules_file
from guard_tpu.core.scopes import RootScope
from guard_tpu.core.evaluator import eval_rules_file
from guard_tpu.core.values import from_plain
from guard_tpu.ops.encoder import encode_batch
from guard_tpu.ops.ir import Unlowerable, compile_rules_file
from guard_tpu.ops.kernels import BatchEvaluator

STATUS = {0: "PASS", 1: "FAIL", 2: "SKIP"}


def _oracle(rf, doc):
    from guard_tpu.commands.report import rule_statuses_from_root

    scope = RootScope(rf, doc)
    eval_rules_file(rf, scope, None)
    root = scope.reset_recorder().extract()
    return {n: s.value for n, s in rule_statuses_from_root(root).items()}


def _differential(rules_text, docs_plain, expect_host=0, allow_unsure=False):
    rf = parse_rules_file(rules_text, "cov.guard")
    docs = [from_plain(d) for d in docs_plain]
    batch, interner = encode_batch(docs)
    compiled = compile_rules_file(rf, interner)
    assert len(compiled.host_rules) == expect_host, [
        r.rule_name for r in compiled.host_rules
    ]
    if not compiled.rules:
        return
    evaluator = BatchEvaluator(compiled)
    statuses = evaluator(batch)
    unsure = evaluator.last_unsure
    for di, doc in enumerate(docs):
        oracle = _oracle(rf, doc)
        for ri, crule in enumerate(compiled.rules):
            if unsure is not None and bool(unsure[di, ri]):
                assert allow_unsure, "unexpected unsure flag"
                continue
            dev = STATUS[int(statuses[di, ri])]
            assert dev == oracle[crule.name], (
                f"doc {di} ({docs_plain[di]}) rule {crule.name}: "
                f"device={dev} oracle={oracle[crule.name]}"
            )


# ---------------------------------------------------------------------------
# keys filters: the grammar (like the reference's, parser.rs:810-835)
# only produces ==/!=/in/not-in after `keys` — ordering comparators are
# a parse error, so no ordering lowering gap exists
# ---------------------------------------------------------------------------
def test_keys_ordering_is_a_parse_error_like_reference():
    from guard_tpu.core.errors import ParseError

    with pytest.raises(ParseError):
        parse_rules_file("rule r { Resources[ keys > 'm' ].x exists }", "x")


# ---------------------------------------------------------------------------
# literal variable key interpolation
# ---------------------------------------------------------------------------
def test_literal_var_key_interpolation():
    _differential(
        """
let wanted = ['BucketA', 'BucketB']
let single = 'BucketA'

rule both_encrypted { Resources.%wanted.Encrypted == true }
rule one_encrypted { Resources.%single.Encrypted exists }
""",
        [
            {
                "Resources": {
                    "BucketA": {"Encrypted": True},
                    "BucketB": {"Encrypted": True},
                }
            },
            {"Resources": {"BucketA": {"Encrypted": True}}},  # B missing
            {"Resources": {"BucketA": {"Encrypted": False}, "BucketB": {"Encrypted": True}}},
            {"Resources": "not-a-map"},
        ],
    )


# ---------------------------------------------------------------------------
# query variable key interpolation
# ---------------------------------------------------------------------------
def test_query_var_key_interpolation():
    _differential(
        """
let names = Selection.targets

rule selected_typed { Resources.%names.Type == 'Good' }
rule selected_exists { Resources.%names exists }
""",
        [
            {
                "Selection": {"targets": ["a", "b"]},
                "Resources": {"a": {"Type": "Good"}, "b": {"Type": "Good"}},
            },
            {
                "Selection": {"targets": ["a", "b"]},
                "Resources": {"a": {"Type": "Good"}},  # b missing
            },
            {
                "Selection": {"targets": ["a"]},
                "Resources": {"a": {"Type": "Bad"}, "b": {"Type": "Good"}},
            },
            {
                "Selection": {"targets": "a"},  # scalar string value
                "Resources": {"a": {"Type": "Good"}},
            },
        ],
    )


def test_query_var_interpolation_non_string_flags_unsure():
    rules = """
let names = Selection.targets

rule r { Resources.%names exists }
"""
    rf = parse_rules_file(rules, "x")
    docs = [from_plain({"Selection": {"targets": [3]}, "Resources": {"a": 1}})]
    batch, interner = encode_batch(docs)
    compiled = compile_rules_file(rf, interner)
    assert not compiled.host_rules and compiled.needs_unsure
    evaluator = BatchEvaluator(compiled)
    evaluator(batch)
    assert evaluator.last_unsure is not None and bool(evaluator.last_unsure[0, 0])


# ---------------------------------------------------------------------------
# struct literals (map / nested-list RHS)
# ---------------------------------------------------------------------------
def test_map_literal_rhs():
    _differential(
        """
rule tags_exact { Resources.*.Tags == { env: "prod", owner: "infra" } }
rule in_with_maps { some Resources.*.Tags IN [{ env: "prod", owner: "infra" }, { env: "qa" }] }
""",
        [
            {"Resources": {"a": {"Tags": {"env": "prod", "owner": "infra"}}}},
            {"Resources": {"a": {"Tags": {"owner": "infra", "env": "prod"}}}},
            {"Resources": {"a": {"Tags": {"env": "qa"}}}},
            {"Resources": {"a": {"Tags": {"env": "prod"}}}},
            {"Resources": {"a": {"Tags": "prod"}}},
        ],
    )


def test_nested_list_literal_rhs():
    _differential(
        """
rule ports_allowed { some Resources.*.Ports IN [[22, 443], [80]] }
""",
        [
            {"Resources": {"a": {"Ports": [22, 443]}}},
            {"Resources": {"a": {"Ports": [80]}}},
            {"Resources": {"a": {"Ports": [22, 8080]}}},
            {"Resources": {"a": {"Ports": 80}}},
        ],
    )


def test_struct_literals_lower_with_tri_state_columns():
    # round 3: != vs map literal and regex members lower exactly via
    # the host-precomputed compare_eq tri-state columns
    # (encoder.struct_literal_tri); full differential coverage in
    # tests/test_lowering_round3.py
    _differential(
        'rule r { Resources.*.Tags != { env: "prod" } }',
        [
            {"Resources": {"a": {"Tags": {"env": "qa"}}}},
            {"Resources": {"a": {"Tags": {"env": "prod"}}}},
            {"Resources": {"a": {"Tags": "flat"}}},  # raises -> FAIL
        ],
    )
    _differential(
        "rule r { Resources.*.Tags == { env: /pr/ } }",
        [
            {"Resources": {"a": {"Tags": {"env": "prod"}}}},
            {"Resources": {"a": {"Tags": {"env": "qa"}}}},
        ],
    )


# ---------------------------------------------------------------------------
# end-to-end through the backend: both paths agree
# ---------------------------------------------------------------------------
def test_backend_cli_parity_interpolation(tmp_path):
    import json
    import subprocess
    import sys

    rules = tmp_path / "r.guard"
    rules.write_text(
        "let names = Selection.targets\n"
        "rule r { Resources.%names.Type == 'Good' }\n"
    )
    data = tmp_path / "data"
    data.mkdir()
    (data / "d0.json").write_text(
        json.dumps(
            {
                "Selection": {"targets": ["a", "b"]},
                "Resources": {"a": {"Type": "Good"}, "b": {"Type": "Good"}},
            }
        )
    )
    (data / "d1.json").write_text(
        json.dumps(
            {
                "Selection": {"targets": ["a", "b"]},
                "Resources": {"a": {"Type": "Good"}},
            }
        )
    )

    def run(extra):
        return subprocess.run(
            [sys.executable, "-m", "guard_tpu.cli", "validate", "-r",
             str(rules), "-d", str(data), "--structured", "-o", "json",
             "--show-summary", "none"] + extra,
            capture_output=True, text=True, timeout=300,
        )

    cpu = run([])
    tpu = run(["--backend", "tpu"])
    assert cpu.returncode == tpu.returncode == 19
    assert json.loads(cpu.stdout) == json.loads(tpu.stdout)


def test_interpolation_block_let_shadows_file_let():
    """Block-scoped lets shadow file-level lets (BlockScope resolves
    innermost first) — the lowering must match."""
    _differential(
        """
let names = 'FileLevel'

rule shadowed {
    let names = 'BlockLevel'
    Resources.%names exists
}
""",
        [
            {"Resources": {"BlockLevel": 1}},
            {"Resources": {"FileLevel": 1}},
        ],
    )


# ---------------------------------------------------------------------------
# cross-scope root variables (previously host-only)
# ---------------------------------------------------------------------------
def test_root_variable_inside_filter():
    _differential(
        """
let allowed = Parameters.AllowedZones

rule zones_ok {
    Resources.*[ Properties.Zone IN %allowed ] !empty
}
""",
        [
            {"Parameters": {"AllowedZones": ["us-1", "us-2"]},
             "Resources": {"a": {"Type": "T", "Properties": {"Zone": "us-1"}}}},
            {"Parameters": {"AllowedZones": ["us-1"]},
             "Resources": {"a": {"Type": "T", "Properties": {"Zone": "eu-9"}}}},
        ],
        allow_unsure=True,
    )


def test_root_variable_inside_block_body():
    _differential(
        """
let flag = Parameters.Strict

rule strict_typed {
    Resources.* {
        Type exists
        %flag == true
    }
}
""",
        [
            {"Parameters": {"Strict": True},
             "Resources": {"a": {"Type": "T"}, "b": {"Type": "U"}}},
            {"Parameters": {"Strict": False},
             "Resources": {"a": {"Type": "T"}}},
            {"Resources": {"a": {"Type": "T"}}},  # unresolved var
        ],
    )


def test_root_variable_unary_inside_filter():
    _differential(
        """
let probe = Parameters.Probe

rule gated_sel {
    Resources.*[ %probe exists Type == 'T' ] !empty
}
""",
        [
            {"Parameters": {"Probe": 1},
             "Resources": {"a": {"Type": "T"}}},
            {"Resources": {"a": {"Type": "T"}}},
        ],
    )
