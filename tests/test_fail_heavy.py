"""Fail-heavy mitigation (round-3 VERDICT item 4): `validate
--backend tpu --statuses-only` skips the oracle fail-rerun, and large
rerun sets fan out over a process pool with identical output."""

import json

import pytest

from guard_tpu.cli import run
from guard_tpu.core.errors import GuardError
from guard_tpu.utils.io import Reader, Writer

RULES = (
    "let b = Resources.*[ Type == 'AWS::S3::Bucket' ]\n"
    "rule sse when %b !empty { %b.Properties.Enc == true }\n"
    "rule named { Resources.* { Type exists } }\n"
)


def _mk_corpus(tmp_path, n, fail_every=2):
    rules = tmp_path / "r.guard"
    rules.write_text(RULES)
    data = tmp_path / "data"
    data.mkdir()
    for i in range(n):
        enc = (i % fail_every) != 0
        (data / f"t{i:03d}.json").write_text(json.dumps({
            "Resources": {
                "b": {"Type": "AWS::S3::Bucket",
                      "Properties": {"Enc": enc}},
            }
        }))
    return rules, data


def _run(args):
    w = Writer.buffered()
    rc = run(args, writer=w, reader=Reader())
    return rc, w.out.getvalue(), w.err.getvalue()


def test_statuses_only_exit_codes_and_summary(tmp_path):
    rules, data = _mk_corpus(tmp_path, 6)
    rc_full, out_full, _ = _run([
        "validate", "-r", str(rules), "-d", str(data), "--backend", "tpu",
    ])
    rc_so, out_so, _ = _run([
        "validate", "-r", str(rules), "-d", str(data), "--backend", "tpu",
        "--statuses-only",
    ])
    assert rc_full == rc_so == 19
    # identical per-file status and per-rule summary-table lines; the
    # full mode additionally prints per-clause detail, statuses-only
    # by design does not
    def summary_lines(s):
        return [
            l for l in s.splitlines()
            if "Status = " in l
            or l.strip().startswith(("sse", "named", "r.guard"))
        ]

    assert summary_lines(out_so) == summary_lines(out_full)
    assert "Status = FAIL" in out_so


def test_statuses_only_conflicts():
    with pytest.raises(GuardError):
        from guard_tpu.commands.validate import Validate

        Validate(rules=["x"], backend="cpu", statuses_only=True)._validate_args()
    with pytest.raises(GuardError):
        from guard_tpu.commands.validate import Validate

        Validate(
            rules=["x"], backend="tpu", statuses_only=True, verbose=True
        )._validate_args()


def test_pooled_rerun_matches_inline(tmp_path, monkeypatch):
    import os

    import guard_tpu.ops.backend as backend

    rules, data = _mk_corpus(tmp_path, 60, fail_every=1)  # all fail
    # the native records engine serves rich reruns when available —
    # disable it so the Python pool path is actually exercised
    import guard_tpu.ops.native_oracle as no_mod
    from guard_tpu.ops.native_oracle import NativeUnsupported

    def refuse(rf):
        raise NativeUnsupported("disabled: exercising the python pool")

    monkeypatch.setattr(no_mod, "NativeOracle", refuse)
    # force the pool on (min jobs low; this CI box reports 1 CPU)
    monkeypatch.setattr(backend, "_POOL_MIN_JOBS", 8)
    monkeypatch.setattr(os, "cpu_count", lambda: 4)
    called = {}
    orig = backend._run_oracle_jobs

    def spy(rules_key, rule_file, jobs, workers):
        called["jobs"] = len(jobs)
        return orig(rules_key, rule_file, jobs, workers)

    monkeypatch.setattr(backend, "_run_oracle_jobs", spy)
    rc_pool, out_pool, err_pool = _run([
        "validate", "-r", str(rules), "-d", str(data), "--backend", "tpu",
    ])
    assert called.get("jobs") == 60

    monkeypatch.setattr(backend, "_POOL_MIN_JOBS", 10**9)  # force inline
    rc_inline, out_inline, err_inline = _run([
        "validate", "-r", str(rules), "-d", str(data), "--backend", "tpu",
    ])
    assert rc_pool == rc_inline == 19
    assert out_pool == out_inline
    assert err_pool == err_inline
