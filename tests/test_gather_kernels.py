"""Differential tests for the O(N) gather/segment-sum traversal
formulation (round-3 VERDICT item 1): the same batches must produce
bit-identical statuses under the one-hot and gather formulations, and
documents beyond the old 8192-node ceiling must evaluate ON DEVICE for
rule files without pairwise matrices."""

import numpy as np
import pytest

import guard_tpu.ops.kernels as kernels
from guard_tpu.core.evaluator import eval_rules_file
from guard_tpu.core.parser import parse_rules_file
from guard_tpu.core.scopes import RootScope
from guard_tpu.core.values import from_plain
from guard_tpu.ops.encoder import (
    NODE_BUCKETS,
    NODE_BUCKETS_EXTENDED,
    Interner,
    encode_batch,
    split_batch_by_size,
)
from guard_tpu.ops.ir import compile_rules_file
from guard_tpu.ops.kernels import BatchEvaluator

STATUS = {0: "PASS", 1: "FAIL", 2: "SKIP"}

RULES = """
let buckets = Resources.*[ Type == "AWS::S3::Bucket" ]

rule s3_sse when %buckets !empty {
    %buckets.Properties.BucketEncryption exists
    %buckets.Properties.BucketEncryption.ServerSideEncryptionConfiguration[*].ServerSideEncryptionByDefault.SSEAlgorithm IN ["aws:kms", "AES256"]
}

rule has_tags {
    Resources.* { Properties.Tags !empty  OR  Type == "AWS::IAM::Role" }
}

rule deep_walk {
    Resources.*.Properties.Nested.Inner.Leaf == "v"  OR
    Resources.* empty
}
"""


def _mk_doc(n_resources, with_enc=True, deep=0):
    res = {}
    for i in range(n_resources):
        props = {
            "Tags": [{"Key": "k%d" % i, "Value": "v"}],
        }
        if with_enc:
            props["BucketEncryption"] = {
                "ServerSideEncryptionConfiguration": [
                    {"ServerSideEncryptionByDefault": {"SSEAlgorithm": "aws:kms"}}
                ]
            }
        res["r%d" % i] = {"Type": "AWS::S3::Bucket", "Properties": props}
    # optional deep chain to inflate node count/depth
    cur = {}
    node = cur
    for _ in range(deep):
        nxt = {}
        node["d"] = nxt
        node = nxt
    node["end"] = 1
    if deep:
        res["deep"] = {"Type": "X", "Properties": {"Chain": cur}}
    return {"Resources": res}


def _oracle(rf, doc):
    from guard_tpu.commands.report import rule_statuses_from_root

    scope = RootScope(rf, doc)
    eval_rules_file(rf, scope, None)
    root = scope.reset_recorder().extract()
    return {n: s.value for n, s in rule_statuses_from_root(root).items()}


def _eval_with_threshold(compiled, batch, threshold, monkeypatch):
    monkeypatch.setattr(kernels, "GATHER_MIN_NODES", threshold)
    # the CPU override would otherwise force gather at every bucket,
    # defeating the one-hot side of the comparison
    monkeypatch.setattr(kernels, "GATHER_ALWAYS_ON_CPU", False)
    ev = BatchEvaluator(compiled)
    return ev(batch)


def test_gather_matches_onehot_and_oracle(monkeypatch):
    rf = parse_rules_file(RULES, "g.guard")
    docs_plain = [
        _mk_doc(3),
        _mk_doc(2, with_enc=False),
        _mk_doc(1, deep=40),
        {"Resources": {}},
    ]
    docs = [from_plain(d) for d in docs_plain]
    batch, interner = encode_batch(docs)
    compiled = compile_rules_file(rf, interner)
    assert not compiled.host_rules

    onehot = _eval_with_threshold(compiled, batch, 1 << 30, monkeypatch)
    gather = _eval_with_threshold(compiled, batch, 1, monkeypatch)
    assert np.array_equal(onehot, gather)

    for di, doc in enumerate(docs):
        oracle = _oracle(rf, doc)
        for ri, crule in enumerate(compiled.rules):
            assert STATUS[int(gather[di, ri])] == oracle[crule.name], (
                di, crule.name,
            )


def test_gather_matches_onehot_unresolved_heavy(monkeypatch):
    # UnResolved accounting paths: missing keys, empty containers,
    # index steps, filters over mixed shapes
    rules = """
rule r1 { Resources.*.Properties.Missing exists }
rule r2 { Resources.*.Properties.Arr[2] == 1 }
rule r3 { Resources.*[ Properties.Kind == "x" ].Properties.Val >= 10 }
rule r4 { Resources.* { Properties.Arr[*] < 100 } }
"""
    rf = parse_rules_file(rules, "g2.guard")
    docs_plain = [
        {"Resources": {"a": {"Properties": {"Arr": [1, 2, 3], "Kind": "x",
                                            "Val": 12}}}},
        {"Resources": {"a": {"Properties": {"Arr": [1]}},
                       "b": {"Properties": {"Kind": "x", "Val": 5}}}},
        {"Resources": {"a": {"Properties": {}}, "b": 3}},
    ]
    docs = [from_plain(d) for d in docs_plain]
    batch, interner = encode_batch(docs)
    compiled = compile_rules_file(rf, interner)
    assert not compiled.host_rules

    onehot = _eval_with_threshold(compiled, batch, 1 << 30, monkeypatch)
    gather = _eval_with_threshold(compiled, batch, 1, monkeypatch)
    assert np.array_equal(onehot, gather)
    for di, doc in enumerate(docs):
        oracle = _oracle(rf, doc)
        for ri, crule in enumerate(compiled.rules):
            assert STATUS[int(gather[di, ri])] == oracle[crule.name]


def test_extended_buckets_keep_16k_docs_on_device():
    # a ~16k-node document stays on device for a non-pairwise rule file
    rules = 'rule big { Resources.* { Type exists } }'
    rf = parse_rules_file(rules, "big.guard")
    n_res = 2100  # ~7 nodes per resource -> >14k nodes
    doc = from_plain(_mk_doc(n_res, with_enc=False))
    batch, interner = encode_batch([doc])
    assert batch.n_nodes > NODE_BUCKETS[-1]
    compiled = compile_rules_file(rf, interner)
    assert not compiled.host_rules
    assert not compiled.needs_pairwise

    groups, oversize = split_batch_by_size(batch, NODE_BUCKETS_EXTENDED)
    assert len(oversize) == 0 and len(groups) == 1

    sub, idx = groups[0]
    statuses = BatchEvaluator(compiled)(sub)
    oracle = _oracle(rf, doc)
    assert STATUS[int(statuses[0, 0])] == oracle["big"]


def test_pairwise_rules_keep_standard_ceiling():
    rules = "rule r { x == y }"  # query RHS -> pairwise matrices
    rf = parse_rules_file(rules, "p.guard")
    interner = Interner()
    _, interner = encode_batch([from_plain({"x": 1, "y": 1})], interner)
    compiled = compile_rules_file(rf, interner)
    assert compiled.needs_pairwise


def test_backend_evaluates_16k_doc_without_host_fallback(monkeypatch):
    from guard_tpu.parallel import mesh as pmesh

    rules = 'rule big { Resources.* { Type exists } }'
    rf = parse_rules_file(rules, "big.guard")
    doc = from_plain(_mk_doc(2100, with_enc=False))
    batch, interner = encode_batch([doc])
    compiled = compile_rules_file(rf, interner)
    ev = BatchEvaluator(compiled)
    statuses, unsure, host_docs = pmesh.evaluate_bucketed(
        ev, len(compiled.rules), batch
    )
    assert host_docs == set()
    assert STATUS[int(statuses[0, 0])] == "PASS"


def test_top_bucket_33k_nodes_on_device():
    # a ~33k-node document exercises the 65536 top bucket end to end
    rules = 'rule big { Resources.* { Type exists } }\n' \
            'rule enc { some Resources.*.Properties.Size >= 0 }'
    rf = parse_rules_file(rules, "big2.guard")
    n_res = 4700  # ~7 nodes per resource -> ~33k nodes
    doc = from_plain(_mk_doc(n_res, with_enc=False))
    batch, interner = encode_batch([doc])
    assert batch.n_nodes > 16384
    compiled = compile_rules_file(rf, interner)
    assert not compiled.host_rules and not compiled.needs_pairwise
    groups, oversize = split_batch_by_size(batch, NODE_BUCKETS_EXTENDED)
    assert len(oversize) == 0 and len(groups) == 1
    sub, _ = groups[0]
    statuses = BatchEvaluator(compiled)(sub)
    oracle = _oracle(rf, doc)
    for ri, crule in enumerate(compiled.rules):
        assert STATUS[int(statuses[0, ri])] == oracle[crule.name]


def test_empty_unres_walk_emits_no_scatter():
    """A walk that records no UnResolved events (an RHS of just
    StepFnVar) must finalize to a CONSTANT, not an all-constant
    segment_sum: the degenerate scatter (zero weights at constant zero
    indices) crashes the TPU AOT compiler (scatter_emitter.cc CHECK,
    reproduced round 5 on v5e)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from guard_tpu.ops import kernels
    from guard_tpu.ops.ir import StepFnVar

    n = 64
    arrays = {
        "node_kind": jnp.zeros(n, jnp.int32),
        "node_parent": jnp.zeros(n, jnp.int32),
        "scalar_id": jnp.zeros(n, jnp.int32),
        "num_hi": jnp.zeros(n, jnp.int32),
        "num_lo": jnp.zeros(n, jnp.int32),
        "child_count": jnp.zeros(n, jnp.int32),
        "node_key_id": jnp.zeros(n, jnp.int32),
        "node_index": jnp.zeros(n, jnp.int32),
        "node_parent_kind": jnp.zeros(n, jnp.int32),
        "fn_origin": jnp.full(n, -1, jnp.int32),
    }

    def walk(sel):
        d = kernels._DocArrays(arrays, gather_mode=True)
        return kernels.run_steps(
            d, [StepFnVar(key_id=-1000, per_origin=True)], sel
        )

    jaxpr = jax.make_jaxpr(walk)(jnp.zeros(n, jnp.int32))
    prims = [str(e.primitive) for e in jaxpr.jaxpr.eqns]
    assert "scatter-add" not in prims and "scatter" not in prims, prims
    # and the unres output is the structural zero vector
    _, unres = walk(jnp.zeros(n, jnp.int32))
    assert np.asarray(unres).sum() == 0
