"""Large-document device path: docs up to the 8192-node bucket evaluate
on device (VERDICT round 1, item 2 — previously >2048 nodes fell back to
the CPU oracle) and stay bit-exact against the oracle."""

import numpy as np

from guard_tpu.core.parser import parse_rules_file
from guard_tpu.core.scopes import RootScope
from guard_tpu.core.evaluator import eval_rules_file
from guard_tpu.core.values import from_plain
from guard_tpu.ops.encoder import NODE_BUCKETS, encode_batch, split_batch_by_size
from guard_tpu.ops.ir import compile_rules_file
from guard_tpu.ops.kernels import BatchEvaluator

RULES = """
let creates = resource_changes[ change.actions[*] == 'create' ]

rule no_destroys when resource_changes exists {
    resource_changes[*].change.actions[*] != 'delete'
}

rule buckets_private when %creates !empty {
    resource_changes[ type == 'aws_s3_bucket' ].change.after.acl != 'public-read'
}

rule deep_leaf_tagged when %creates !empty {
    some resource_changes[*].change.after.tags.env == 'prod'
}
"""

STATUS = {0: "PASS", 1: "FAIL", 2: "SKIP"}


def _big_plan(rng, n_changes: int, depth: int) -> dict:
    changes = []
    for j in range(n_changes):
        after = {
            "acl": str(rng.choice(["private", "public-read"])),
            "tags": {"env": str(rng.choice(["prod", "qa"]))},
        }
        node = after
        for k in range(depth):
            node[f"n{k}"] = {"leaf": f"v{j}-{k}", "idx": int(k)}
            node = node[f"n{k}"]
        changes.append(
            {
                "address": f"r{j}",
                "type": str(rng.choice(["aws_s3_bucket", "aws_instance"])),
                "change": {
                    "actions": [str(rng.choice(["create", "update", "delete"]))],
                    "after": after,
                },
            }
        )
    return {"resource_changes": changes}


def _oracle(rf, doc):
    from guard_tpu.commands.report import rule_statuses_from_root

    scope = RootScope(rf, doc)
    eval_rules_file(rf, scope, None)
    root = scope.reset_recorder().extract()
    return {n: s.value for n, s in rule_statuses_from_root(root).items()}


def test_4096_and_8192_buckets_stay_on_device_and_match_oracle():
    rng = np.random.default_rng(11)
    rf = parse_rules_file(RULES, "big.guard")
    # ~40 nodes per change: 80 changes -> ~3.3k nodes (4096 bucket),
    # 180 changes -> ~7.4k nodes (8192 bucket), 16 -> small bucket
    docs_plain = [
        _big_plan(rng, 16, 6),
        _big_plan(rng, 80, 6),
        _big_plan(rng, 180, 6),
    ]
    docs = [from_plain(p) for p in docs_plain]
    batch, interner = encode_batch(docs)
    n_real = (batch.node_kind >= 0).sum(axis=1)
    assert n_real[1] > 2048 and n_real[1] <= 4096
    assert n_real[2] > 4096 and n_real[2] <= 8192

    groups, oversize = split_batch_by_size(batch)
    assert len(oversize) == 0, "all three docs must stay on device"
    bucket_sizes = sorted(sub.n_nodes for sub, _ in groups)
    # the last bucket is capped at the batch's own padded width
    assert bucket_sizes[-2] == 4096
    assert int(n_real[2]) <= bucket_sizes[-1] <= 8192

    compiled = compile_rules_file(rf, interner)
    assert not compiled.host_rules
    evaluator = BatchEvaluator(compiled)
    statuses = np.full((batch.n_docs, len(compiled.rules)), 2, np.int8)
    for sub, idx in groups:
        statuses[idx] = evaluator(sub)

    for di, doc in enumerate(docs):
        oracle = _oracle(rf, doc)
        for ri, crule in enumerate(compiled.rules):
            assert STATUS[int(statuses[di, ri])] == oracle[crule.name], (
                f"doc {di} rule {crule.name}"
            )


def test_beyond_last_bucket_routes_to_oracle():
    rng = np.random.default_rng(12)
    doc = from_plain(_big_plan(rng, 300, 6))
    batch, _ = encode_batch([doc])
    assert (batch.node_kind[0] >= 0).sum() > NODE_BUCKETS[-1]
    groups, oversize = split_batch_by_size(batch)
    assert set(int(i) for i in oversize) == {0} and not groups


PAIRWISE_RULES = """
let names = Settings.*

rule q_rhs when resource_changes exists {
    some resource_changes[*].change.after.tags.env ==
        resource_changes[*].change.after.acl
}

rule q_in when resource_changes exists {
    resource_changes[*].change.after.tags.env IN
        resource_changes[*].change.after.allowed
}

rule interp when Settings exists {
    Top.%names exists
}

rule ordering when resource_changes exists {
    some resource_changes[*].change.after.rank <
        resource_changes[*].change.after.cap
}
"""


def test_33k_node_documents_with_pairwise_rules_stay_on_device():
    """VERDICT r4 item 4: query-RHS compares, IN containment, key
    interpolation and ordering against a query RHS all evaluate ON
    DEVICE for documents far beyond the old 8,192-node pairwise
    ceiling — the gather-mode sorted-set formulations never build an
    (N, N) matrix."""
    from guard_tpu.parallel.mesh import ShardedBatchEvaluator

    rng = np.random.default_rng(13)

    def plan(n_changes):
        p = _big_plan(rng, n_changes, 6)
        for j, ch in enumerate(p["resource_changes"]):
            after = ch["change"]["after"]
            after["allowed"] = ["private", f"x{j % 7}"]
            after["rank"] = int(rng.integers(0, 50))
            after["cap"] = int(rng.integers(0, 50))
        p["Settings"] = {"s1": "alpha", "s2": "beta"}
        p["Top"] = {"alpha": 1} if n_changes % 2 else {"gamma": 1}
        return p

    # ~48 nodes per change: 640 -> ~31k nodes (32768 bucket)
    docs_plain = [plan(640), plan(25)]
    docs = [from_plain(p) for p in docs_plain]
    batch, interner = encode_batch(docs)
    n_real = (batch.node_kind >= 0).sum(axis=1)
    assert n_real[0] > 16384, int(n_real[0])

    rf = parse_rules_file(PAIRWISE_RULES, "pairwise.guard")
    compiled = compile_rules_file(rf, interner)
    assert not compiled.host_rules
    assert compiled.needs_pairwise
    ev = ShardedBatchEvaluator(compiled)
    statuses, unsure, host_docs = ev.evaluate_bucketed(batch)
    assert not host_docs, "33k-node doc must stay on device"

    # the subset-mode escape hatch must not swallow anything here:
    # these shapes carry no list-vs-list IN pairs, so EVERY (doc,
    # rule) decides on device — the feature this test pins
    assert unsure.sum() == 0, unsure
    for di, doc in enumerate(docs):
        oracle = _oracle(rf, doc)
        for ri, crule in enumerate(compiled.rules):
            assert STATUS[int(statuses[di, ri])] == oracle[crule.name], (
                f"doc {di} rule {crule.name}"
            )
