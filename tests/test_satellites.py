"""Satellite surfaces: the npm wrapper's CLI contract, the library
embedding example, and the install-script smoke path (SURVEY.md §2.2)."""

import json
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent


def test_ts_lib_cli_contract(tmp_path):
    """ts_lib/index.ts drives `validate --structured -S none -o sarif
    -r <files> -d <files>`; that invocation must emit parseable SARIF
    and the documented exit codes."""
    rules = tmp_path / "r.guard"
    rules.write_text("rule has_res {\n  Resources !empty\n}\n")
    good = tmp_path / "good.json"
    good.write_text(json.dumps({"Resources": {"a": 1}}))
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"Other": 1}))

    from guard_tpu.cli import run
    from guard_tpu.utils.io import Reader, Writer

    w = Writer.buffered()
    code = run(
        [
            "validate", "--structured", "-S", "none", "-o", "sarif",
            "-r", str(rules), "-d", str(good), str(bad),
        ],
        writer=w,
        reader=Reader.from_string(""),
    )
    assert code == 19  # EXIT_CODES.validationFailure in ts_lib/index.ts
    sarif = json.loads(w.stripped())
    assert sarif["version"] == "2.1.0"
    assert sarif["runs"][0]["results"], "failing doc must produce results"

    # the TS source must reference exactly this surface
    ts = (REPO / "ts_lib" / "index.ts").read_text()
    for fragment in ('"--structured"', '"sarif"', "validationFailure: 19"):
        assert fragment in ts


def test_library_example_runs():
    out = subprocess.run(
        [sys.executable, str(REPO / "examples" / "library.py")],
        capture_output=True,
        text=True,
        env={"PYTHONPATH": str(REPO), "PATH": "/usr/bin:/bin:/usr/local/bin"},
        timeout=120,
    )
    assert out.returncode == 0, out.stderr
    assert "run_checks ->" in out.stdout
    assert "builder payload exit code: 19" in out.stdout


def test_install_script_payload_smoke():
    """The smoke payload baked into install-guard-tpu.sh must pass."""
    from guard_tpu.cli import run
    from guard_tpu.utils.io import Reader, Writer

    payload = '{"rules":["rule ok { this exists }"],"data":["{\\"a\\":1}"]}'
    w = Writer.buffered()
    code = run(
        ["validate", "--payload", "-S", "none"],
        writer=w,
        reader=Reader.from_string(payload),
    )
    assert code == 0
