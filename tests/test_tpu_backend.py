"""TPU backend: encoder round-trip, kernel parity vs the CPU oracle
(differential corpus + property-style generated docs), and mesh-sharded
execution on a virtual 8-device CPU mesh."""

import pathlib

import numpy as np
import pytest
import yaml

import jax

from guard_tpu.core.parser import parse_rules_file
from guard_tpu.core.scopes import RootScope
from guard_tpu.core.values import from_plain
from guard_tpu.ops.encoder import Interner, encode_batch, encode_document
from guard_tpu.ops.ir import compile_rules_file
from guard_tpu.ops.kernels import evaluate_batch

STATUS = {0: "PASS", 1: "FAIL", 2: "SKIP"}


def cpu_status(rf, doc, rule_name):
    return RootScope(rf, doc).rule_status(rule_name).value


def tpu_statuses(rf, docs):
    from guard_tpu.ops.kernels import BatchEvaluator

    batch, interner = encode_batch(docs)
    compiled = compile_rules_file(rf, interner)
    if not compiled.rules:
        return None, compiled
    ev = BatchEvaluator(compiled)
    statuses = ev(batch)
    tpu_statuses.last_unsure = ev.last_unsure
    return statuses, compiled


def assert_parity(rules_text, doc_dicts):
    rf = parse_rules_file(rules_text, "t.guard")
    docs = [from_plain(d) for d in doc_dicts]
    statuses, compiled = tpu_statuses(rf, docs)
    unsure = tpu_statuses.last_unsure
    assert statuses is not None, "rule should be lowerable"
    for di, doc in enumerate(docs):
        for ri, crule in enumerate(compiled.rules):
            if unsure is not None and bool(unsure[di, ri]):
                continue  # kernel routes these to the oracle by design
            cpu = cpu_status(rf, doc, crule.name)
            tpu = STATUS[int(statuses[di, ri])]
            assert cpu == tpu, f"doc {di} rule {crule.name}: cpu={cpu} tpu={tpu}"


def test_encoder_roundtrip_shapes():
    doc = from_plain({"a": {"b": [1, "x", True]}, "c": None})
    interner = Interner()
    enc = encode_document(doc, interner)
    assert enc.n_nodes == 7
    assert enc.n_edges == 6
    assert "x" in interner.strings


def test_simple_type_select_parity():
    rules = (
        "let buckets = Resources.*[ Type == 'AWS::S3::Bucket' ]\n"
        "rule sse when %buckets !empty {\n"
        "  %buckets.Properties.BucketEncryption exists\n"
        "}\n"
    )
    assert_parity(
        rules,
        [
            {},
            {"Resources": {}},
            {"Resources": {"b": {"Type": "AWS::S3::Bucket"}}},
            {
                "Resources": {
                    "b": {
                        "Type": "AWS::S3::Bucket",
                        "Properties": {"BucketEncryption": {"x": 1}},
                    }
                }
            },
            {"Resources": {"b": {"Type": "Other"}}},
        ],
    )


def test_in_and_range_parity():
    rules = (
        "rule ports {\n"
        "  Resources.*.Properties.Port IN r[0,1024)\n"
        "  Resources.*.Properties.Type IN ['a', 'b']\n"
        "}\n"
    )
    docs = [
        {"Resources": {"x": {"Properties": {"Port": p, "Type": t}}}}
        for p, t in [(80, "a"), (2000, "b"), (1024, "a"), (0, "c"), (10, "b")]
    ]
    assert_parity(rules, docs)


def test_regex_and_not_parity():
    rules = (
        "rule r {\n"
        "  Resources.*.Name == /^prod-/\n"
        "  Resources.*.Name != /secret/\n"
        "}\n"
    )
    docs = [
        {"Resources": {"x": {"Name": n}}}
        for n in ["prod-1", "dev-1", "prod-secret", "prod-x"]
    ] + [{"Resources": {"x": {"Name": 5}}}]
    assert_parity(rules, docs)


def test_some_vs_all_parity():
    rules = (
        "rule allof {\n  Resources.*.Tags[*].Key == 'env'\n}\n"
        "rule someof {\n  some Resources.*.Tags[*].Key == 'env'\n}\n"
    )
    docs = [
        {"Resources": {"x": {"Tags": [{"Key": "env"}, {"Key": "app"}]}}},
        {"Resources": {"x": {"Tags": [{"Key": "env"}]}}},
        {"Resources": {"x": {"Tags": [{"Key": "app"}]}}},
        {"Resources": {"x": {"Tags": []}}},
        {"Resources": {"x": {}}},
    ]
    assert_parity(rules, docs)


def test_block_clause_parity():
    rules = (
        "Resources.*[ Type == 'T' ] {\n"
        "  Properties.A exists\n"
        "  Properties.B == 1 or Properties.C == 2\n"
        "}\n"
    )
    docs = [
        {"Resources": {"x": {"Type": "T", "Properties": {"A": 1, "B": 1}}}},
        {"Resources": {"x": {"Type": "T", "Properties": {"A": 1, "C": 2}}}},
        {"Resources": {"x": {"Type": "T", "Properties": {"A": 1, "B": 9, "C": 9}}}},
        {"Resources": {"x": {"Type": "T", "Properties": {"B": 1}}}},
        {"Resources": {"x": {"Type": "U"}}},
    ]
    assert_parity(rules, docs)


def test_named_rule_dependency_parity():
    rules = (
        "rule base {\n  Resources exists\n}\n"
        "rule dep when base {\n  Resources.x.T == 1\n}\n"
        "rule neg {\n  not base\n}\n"
    )
    docs = [{"Resources": {"x": {"T": 1}}}, {"Resources": {"x": {"T": 2}}}, {}]
    assert_parity(rules, docs)


def test_keys_filter_parity():
    rules = "rule r {\n  Resources.x.Cond[ keys == /aws:/ ] !empty\n}\n"
    docs = [
        {"Resources": {"x": {"Cond": {"aws:src": 1}}}},
        {"Resources": {"x": {"Cond": {"other": 1}}}},
        {"Resources": {"x": {}}},
    ]
    assert_parity(rules, docs)


def test_empty_checks_parity():
    rules = (
        "rule r {\n"
        "  Resources !empty\n"
        "  Resources.x.Tags empty or Resources.x.Tags !exists\n"
        "}\n"
    )
    docs = [
        {"Resources": {"x": {"Tags": []}}},
        {"Resources": {"x": {"Tags": [1]}}},
        {"Resources": {"x": {}}},
        {},
    ]
    assert_parity(rules, docs)


def test_parameterized_rule_call_parity():
    rules = (
        "rule kms_key_check(topics) {\n"
        "  %topics.Properties.Kms exists\n"
        "  %topics.Properties.Kms == /^arn:/\n"
        "}\n"
        "rule caller {\n"
        "  kms_key_check(Resources.*[ Type == 'AWS::SNS::Topic' ])\n"
        "}\n"
    )
    docs = [
        {"Resources": {"t": {"Type": "AWS::SNS::Topic", "Properties": {"Kms": "arn:aws:x"}}}},
        {"Resources": {"t": {"Type": "AWS::SNS::Topic", "Properties": {"Kms": "alias/x"}}}},
        {"Resources": {"t": {"Type": "AWS::SNS::Topic", "Properties": {}}}},
        {"Resources": {"t": {"Type": "Other"}}},
        {},
    ]
    assert_parity(rules, docs)


def test_parameterized_rule_literal_arg_parity():
    rules = (
        "rule enc_is(algos) {\n"
        "  Resources.*.Properties.Alg IN %algos\n"
        "}\n"
        "rule caller {\n"
        "  enc_is(['aws:kms', 'AES256'])\n"
        "}\n"
    )
    docs = [
        {"Resources": {"x": {"Properties": {"Alg": "aws:kms"}}}},
        {"Resources": {"x": {"Properties": {"Alg": "none"}}}},
        {"Resources": {"x": {"Properties": {}}}},
    ]
    assert_parity(rules, docs)


def test_parameterized_rule_with_when_inside_parity():
    rules = (
        "rule sized(vols) {\n"
        "  when %vols !empty {\n"
        "    %vols.Size <= 100\n"
        "  }\n"
        "}\n"
        "rule caller {\n"
        "  sized(Resources.*[ Type == 'V' ])\n"
        "}\n"
    )
    docs = [
        {"Resources": {"v": {"Type": "V", "Size": 50}}},
        {"Resources": {"v": {"Type": "V", "Size": 500}}},
        {"Resources": {"v": {"Type": "W", "Size": 500}}},
        {},
    ]
    assert_parity(rules, docs)


def test_type_block_with_when_conditions_parity():
    rules = (
        "rule r {\n"
        "  AWS::S3::Bucket when Mode == 'strict' {\n"
        "    Properties.Enc exists\n"
        "  }\n"
        "}\n"
    )
    docs = [
        {"Mode": "strict", "Resources": {"b": {"Type": "AWS::S3::Bucket", "Properties": {"Enc": 1}}}},
        {"Mode": "strict", "Resources": {"b": {"Type": "AWS::S3::Bucket", "Properties": {}}}},
        {"Mode": "lax", "Resources": {"b": {"Type": "AWS::S3::Bucket", "Properties": {}}}},
        {"Mode": "strict", "Resources": {"b": {"Type": "Other"}}},
    ]
    assert_parity(rules, docs)


def test_this_in_query_parity():
    rules = "rule r {\n  Resources.*.Name == /^p/\n  this.Resources exists\n}\n"
    docs = [
        {"Resources": {"x": {"Name": "p1"}}},
        {"Resources": {"x": {"Name": "q1"}}},
    ]
    assert_parity(rules, docs)


def test_char_range_never_comparable_parity():
    rules = "rule r {\n  Resources.x.C IN r(a,z)\n}\n"
    docs = [
        {"Resources": {"x": {"C": "m"}}},
        {"Resources": {"x": {"C": 5}}},
    ]
    assert_parity(rules, docs)


def test_in_string_containment_direction_parity():
    # lhs.val in rhs.val — the document value is the needle
    rules = (
        "rule r {\n  Resources.x.V IN 'abcdef'\n}\n"
        "rule rn {\n  Resources.x.V !IN 'abcdef'\n}\n"
    )
    docs = [
        {"Resources": {"x": {"V": v}}} for v in ["abc", "abcdefgh", "zzz", 5]
    ]
    assert_parity(rules, docs)


def test_not_in_scalar_rhs_not_comparable_parity():
    # NotComparable stays FAIL through the `not` inversion; a LIST lhs
    # vs non-list RHS is NotComparable
    rules = (
        "rule r {\n  Resources.x.C !IN r(a,z)\n}\n"
        "rule r2 {\n  Resources.x.L IN r[0,10]\n}\n"
    )
    docs = [
        {"Resources": {"x": {"C": "m", "L": [5]}}},
        {"Resources": {"x": {"C": 5, "L": 5}}},
    ]
    assert_parity(rules, docs)


def test_root_variable_crossing_value_scope_lowers_and_matches():
    # root-bound variables used inside value scopes lower via the
    # evaluate-once-from-root broadcast (previously host-only); the
    # oracle resolves them at the binding scope — statuses must match
    from guard_tpu.ops.ir import compile_rules_file as cmp_rules

    rules = (
        "rule p(a) {\n  Resources.* {\n    Type exists\n    %a == 'strict'\n  }\n}\n"
        "rule caller {\n  p(Config.Mode)\n}\n"
        "let mode = Config.Mode\n"
        "rule filevar {\n  Resources.* {\n    %mode == 'strict'\n  }\n}\n"
    )
    rf = parse_rules_file(rules, "t.guard")
    docs = [
        from_plain({"Config": {"Mode": "strict"}, "Resources": {"r": {"Type": "T"}}}),
        from_plain({"Config": {"Mode": "lax"}, "Resources": {"r": {"Type": "T"}}}),
        from_plain({"Resources": {"r": {"Type": "T"}}}),
    ]
    batch, interner = encode_batch(docs)
    compiled = cmp_rules(rf, interner)
    assert not compiled.host_rules
    assert_parity(rules, [d.to_plain() for d in docs])


def test_string_ordering_parity():
    rules = (
        "rule r {\n  Resources.x.V >= 'm'\n}\n"
        "rule r2 {\n  Resources.x.V < 'm'\n}\n"
        "rule r3 {\n  Resources.x.V > 'm'\n}\n"
        "rule r4 {\n  Resources.x.V <= 'm'\n}\n"
    )
    docs = [
        {"Resources": {"x": {"V": v}}}
        for v in ["a", "m", "z", "mm", 5, True]
    ]
    assert_parity(rules, docs)


def test_query_rhs_eq_parity():
    rules = (
        "rule r {\n  Resources.a.P == Resources.b.P\n}\n"
        "rule rn {\n  Resources.a.P != Resources.b.P\n}\n"
    )
    docs = [
        {"Resources": {"a": {"P": "x"}, "b": {"P": "x"}}},
        {"Resources": {"a": {"P": "x"}, "b": {"P": "y"}}},
        {"Resources": {"a": {"P": 5}, "b": {"P": 5}}},
        {"Resources": {"a": {"P": 5}, "b": {"P": 5.0}}},
        {"Resources": {"a": {"P": [1, 2]}, "b": {"P": [1, 2]}}},
        {"Resources": {"a": {"P": [1, 2]}, "b": {"P": [2, 1]}}},
        {"Resources": {"a": {"P": {"k": 1}}, "b": {"P": {"k": 1}}}},
        {"Resources": {"a": {"P": "x"}, "b": {}}},
        {"Resources": {"a": {}, "b": {}}},
    ]
    assert_parity(rules, docs)


def test_query_rhs_eq_multi_value_sets_parity():
    rules = "rule r {\n  Resources.*.Tags == Allowed.Tags\n}\n"
    docs = [
        {"Resources": {"a": {"Tags": "t1"}, "b": {"Tags": "t2"}},
         "Allowed": {"Tags": ["t1", "t2"]}},
        {"Resources": {"a": {"Tags": "t1"}}, "Allowed": {"Tags": ["t1"]}},
        {"Resources": {"a": {"Tags": "t3"}}, "Allowed": {"Tags": ["t1", "t2"]}},
    ]
    assert_parity(rules, docs)


def test_query_rhs_in_parity():
    rules = (
        "let allowed = Mappings.AllowedValues\n"
        "rule r {\n  Resources.*.Properties.Alg IN %allowed\n}\n"
        "rule rn {\n  Resources.*.Properties.Alg !IN %allowed\n}\n"
    )
    docs = [
        {"Mappings": {"AllowedValues": ["aws:kms", "AES256"]},
         "Resources": {"x": {"Properties": {"Alg": "aws:kms"}}}},
        {"Mappings": {"AllowedValues": ["aws:kms"]},
         "Resources": {"x": {"Properties": {"Alg": "none"}}}},
        {"Mappings": {"AllowedValues": "aws:kms"},
         "Resources": {"x": {"Properties": {"Alg": "aws:kms"}}}},
        {"Mappings": {},
         "Resources": {"x": {"Properties": {"Alg": "aws:kms"}}}},
        {"Mappings": {"AllowedValues": [5, 7]},
         "Resources": {"x": {"Properties": {"Alg": 5}}}},
    ]
    assert_parity(rules, docs)


def test_query_rhs_in_list_list_decided_on_device():
    # round 3: list-vs-list IN no longer flags unsure — the kernel
    # models both containment modes exactly (membership-among-elements
    # when the rhs's first element is a list, subset otherwise);
    # differential coverage in tests/test_lowering_round3.py
    rules = "rule r {\n  Resources.x.L IN Resources.x.Allowed\n}\n"
    rf = parse_rules_file(rules, "t.guard")
    docs = [
        from_plain({"Resources": {"x": {"L": [1, 2], "Allowed": [[2, 1], [3]]}}}),
        from_plain({"Resources": {"x": {"L": "s", "Allowed": ["s", "t"]}}}),
    ]
    statuses, compiled = tpu_statuses(rf, docs)
    unsure = tpu_statuses.last_unsure
    assert compiled.needs_struct_ids
    assert unsure is not None
    assert not bool(unsure[0, 0])
    assert not bool(unsure[1, 0])
    for di in (0, 1):
        assert STATUS[int(statuses[di, 0])] == cpu_status(rf, docs[di], "r")


# ---------------------------------------------------------------------------
# full examples corpus differential
# ---------------------------------------------------------------------------
def _corpus():
    for guard in sorted(
        pathlib.Path("/root/reference/guard-examples").rglob("*.guard")
    ):
        tests = guard.with_name(guard.stem + "-tests.yaml")
        if tests.exists():
            yield pytest.param(guard, tests, id=guard.stem)


@pytest.mark.parametrize("guard,tests", _corpus())
def test_examples_corpus_differential(guard, tests):
    rf = parse_rules_file(guard.read_text(), guard.name)
    specs = yaml.safe_load(tests.read_text()) or []
    docs = [from_plain(s.get("input")) for s in specs]
    if not docs:
        pytest.skip("no specs")
    statuses, compiled = tpu_statuses(rf, docs)
    if statuses is None:
        pytest.skip("no lowerable rules")
    for di, doc in enumerate(docs):
        for ri, crule in enumerate(compiled.rules):
            cpu = cpu_status(rf, doc, crule.name)
            tpu = STATUS[int(statuses[di, ri])]
            assert cpu == tpu, f"{guard.name} doc#{di} {crule.name}"


# ---------------------------------------------------------------------------
# property-style generated documents
# ---------------------------------------------------------------------------
def _gen_doc(rng):
    def val(depth):
        r = rng.random()
        if depth > 2 or r < 0.3:
            return rng.choice(
                ["aws:kms", "AES256", "", "prod-x", 17, 3.5, True, False, None],
            )
        if r < 0.6:
            return [val(depth + 1) for _ in range(rng.integers(0, 3))]
        return {
            rng.choice(["A", "B", "Type", "Enc"]): val(depth + 1)
            for _ in range(rng.integers(0, 3))
        }

    return {
        "Resources": {
            f"r{i}": {
                "Type": str(rng.choice(["AWS::S3::Bucket", "AWS::EC2::Volume"])),
                "Properties": {
                    "Enc": val(0),
                    "Size": int(rng.integers(0, 300)),
                },
            }
            for i in range(rng.integers(0, 3))
        }
    }


def test_generated_docs_differential():
    rng = np.random.default_rng(42)
    rules = (
        "let buckets = Resources.*[ Type == 'AWS::S3::Bucket' ]\n"
        "rule r1 when %buckets !empty {\n"
        "  %buckets.Properties.Enc exists\n"
        "  %buckets.Properties.Size IN r[0,200]\n"
        "}\n"
        "rule r2 {\n  some Resources.*.Properties.Enc == 'aws:kms'\n}\n"
        "rule r3 {\n  Resources.*.Properties.Size <= 100\n}\n"
    )
    rf = parse_rules_file(rules, "gen.guard")
    docs = [from_plain(_gen_doc(rng)) for _ in range(64)]
    statuses, compiled = tpu_statuses(rf, docs)
    assert statuses is not None
    for di, doc in enumerate(docs):
        for ri, crule in enumerate(compiled.rules):
            cpu = cpu_status(rf, doc, crule.name)
            tpu = STATUS[int(statuses[di, ri])]
            assert cpu == tpu, f"gen doc#{di} {crule.name}: cpu={cpu} tpu={tpu}"


# ---------------------------------------------------------------------------
# mesh sharding on the virtual 8-device CPU mesh
# ---------------------------------------------------------------------------
def test_sharded_evaluator_cpu_mesh():
    from guard_tpu.parallel.mesh import ShardedBatchEvaluator, default_mesh

    cpus = jax.devices("cpu")
    if len(cpus) < 2:
        pytest.skip("need multiple cpu devices")
    rules = (
        "let buckets = Resources.*[ Type == 'AWS::S3::Bucket' ]\n"
        "rule sse when %buckets !empty {\n"
        "  %buckets.Properties.Enc == 'aws:kms'\n"
        "}\n"
    )
    rf = parse_rules_file(rules, "")
    docs = [
        from_plain(
            {
                "Resources": {
                    "b": {
                        "Type": "AWS::S3::Bucket",
                        "Properties": {"Enc": "aws:kms" if i % 3 else "AES256"},
                    }
                }
            }
        )
        for i in range(37)
    ]
    batch, interner = encode_batch(docs)
    compiled = compile_rules_file(rf, interner)
    mesh = default_mesh(cpus)
    ev = ShardedBatchEvaluator(compiled, mesh)
    statuses = ev(batch)
    assert statuses.shape == (37, 1)
    for i in range(37):
        expected = "PASS" if i % 3 else "FAIL"
        assert STATUS[int(statuses[i, 0])] == expected
    # summary reduction across the mesh
    st2, counts = ev.with_summary(batch)
    assert counts.shape == (3, 1)
    assert int(counts[0, 0]) + int(counts[1, 0]) == 37


def test_split_batch_by_size_groups_and_oversize():
    from guard_tpu.ops.encoder import split_batch_by_size

    small = {"a": 1}
    medium = {"Resources": {f"r{i}": {"Type": "T", "Properties": {"x": i}} for i in range(30)}}
    # beyond the 8192-node last bucket (each resource is 2 nodes)
    giant = {"Resources": {f"r{i}": {"Type": "T"} for i in range(4200)}}
    docs = [from_plain(d) for d in (small, medium, giant, small)]
    batch, _ = encode_batch(docs)
    groups, oversize = split_batch_by_size(batch)
    assert list(oversize) == [2]
    covered = sorted(int(i) for _, idx in groups for i in idx)
    assert covered == [0, 1, 3]
    for sub, idx in groups:
        # padding shapes shrink to the bucket, content preserved exactly
        assert sub.n_nodes <= batch.n_nodes
        for j, di in enumerate(idx):
            n = int((sub.node_kind[j] >= 0).sum())
            assert n == int((batch.node_kind[di] >= 0).sum())
            np.testing.assert_array_equal(
                sub.node_kind[j, :n], batch.node_kind[di, :n]
            )
            np.testing.assert_array_equal(
                sub.node_key_id[j, :n], batch.node_key_id[di, :n]
            )


def test_bucketed_parity_mixed_sizes():
    """Same statuses whether evaluated as one batch or per size bucket."""
    from guard_tpu.ops.encoder import split_batch_by_size
    from guard_tpu.ops.kernels import BatchEvaluator

    rules = """
let r = Resources.*[ Type == 'AWS::S3::Bucket' ]
rule sse when %r !empty { %r.Properties.Enc == true }
"""
    rf = parse_rules_file(rules, "t.guard")
    doc_dicts = []
    for i in range(6):
        res = {
            f"b{j}": {
                "Type": "AWS::S3::Bucket",
                "Properties": {"Enc": (i + j) % 2 == 0},
            }
            for j in range(1 + 20 * (i % 3))
        }
        doc_dicts.append({"Resources": res})
    docs = [from_plain(d) for d in doc_dicts]
    batch, interner = encode_batch(docs)
    compiled = compile_rules_file(rf, interner)
    ev = BatchEvaluator(compiled)
    whole = ev(batch)
    groups, oversize = split_batch_by_size(batch, buckets=(32, 64, 2048))
    assert len(oversize) == 0 and len(groups) >= 2
    merged = np.full_like(whole, -1)
    for sub, idx in groups:
        merged[idx] = BatchEvaluator(compiled)(sub)
    np.testing.assert_array_equal(whole, merged)
    for di, doc in enumerate(docs):
        cpu = cpu_status(rf, doc, "sse")
        assert STATUS[int(whole[di, 0])] == cpu


def test_backend_routes_oversize_docs_to_oracle(tmp_path):
    """validate --backend tpu agrees with the plain oracle backend when
    the corpus contains a document beyond the largest node bucket."""
    import json

    from guard_tpu.cli import run

    rules = tmp_path / "r.guard"
    rules.write_text(
        "let b = Resources.*[ Type == 'AWS::S3::Bucket' ]\n"
        "rule sse when %b !empty { %b.Properties.Enc == true }\n"
    )
    data = tmp_path / "data"
    data.mkdir()
    (data / "small.json").write_text(json.dumps(
        {"Resources": {"b": {"Type": "AWS::S3::Bucket", "Properties": {"Enc": True}}}}
    ))
    giant = {"Resources": {f"r{i}": {"Type": "X"} for i in range(1100)}}
    giant["Resources"]["b"] = {
        "Type": "AWS::S3::Bucket", "Properties": {"Enc": False}
    }
    (data / "giant.json").write_text(json.dumps(giant))
    code_tpu = run([
        "validate", "--backend", "tpu", "-r", str(rules), "-d", str(data)
    ])
    code_cpu = run(["validate", "-r", str(rules), "-d", str(data)])
    assert code_tpu == code_cpu == 19  # giant doc fails via oracle routing
