"""Second batch of semantic cases ported from the reference's pinned
evaluation suite (guard/src/rules/eval_tests.rs) — rule/doc/expectation
data re-expressed as pytest cases against this framework's oracle.
Each test cites the reference test function it pins."""

import pytest
import yaml

from guard_tpu.core.loader import load_document
from guard_tpu.core.parser import parse_rules_file
from guard_tpu.core.scopes import RootScope
from guard_tpu.core.values import from_plain


def _status(rules, doc, rule=None):
    rf = parse_rules_file(rules, "t.guard")
    scope = RootScope(rf, doc if not isinstance(doc, dict) else from_plain(doc))
    if rule is None:
        from guard_tpu.core.evaluator import eval_rules_file

        return eval_rules_file(rf, scope, None).value
    return scope.rule_status(rule).value


def _clause_status(clause, doc):
    return _status(f"rule t {{ {clause} }}", doc, "t")


IAM_TWO_ROLES = {
    "Resources": {
        "iamrole": {
            "Type": "AWS::IAM::Role",
            "Properties": {
                "PermissionsBoundary": "arn:aws:iam::123456789012:policy/permboundary",
                "AssumeRolePolicyDocument": {
                    "Version": "2021-01-10",
                    "Statement": {
                        "Effect": "Allow",
                        "Principal": "*",
                        "Action": "*",
                        "Resource": "*",
                    },
                },
            },
        },
        "iamRole2": {
            "Type": "AWS::IAM::Role",
            "Properties": {
                "PermissionsBoundary": "arn:aws:iam::123456789112:policy/permboundary",
                "AssumeRolePolicyDocument": {
                    "Version": "2021-01-10",
                    "Statement": {
                        "Effect": "Allow",
                        "Principal": "*",
                        "Action": "*",
                        "Resource": "*",
                    },
                },
                "Tags": [{"Key": "Key", "Value": "Value"}],
            },
        },
    }
}


def test_unintuitive_all_clause_that_skips():
    """eval_tests.rs rules_file_tests_the_unituitive_all_clause_that_skips:
    a when-gate over ALL resources' Tags EXISTS fails on the untagged
    resource, so the inner block SKIPs and the file PASSes."""
    rules = """
let iam_resources = Resources.*[ Type == "AWS::IAM::Role" ]
rule iam_resources_exists {
    %iam_resources !EMPTY
}

rule iam_basic_checks when iam_resources_exists {
    %iam_resources.Properties.AssumeRolePolicyDocument.Version == /(\\d{4})-(\\d{2})-(\\d{2})/
    %iam_resources.Properties.PermissionsBoundary == /arn:aws:iam::(\\d{12}):policy/
    when %iam_resources.Properties.Tags EXISTS
         %iam_resources.Properties.Tags !EMPTY {

        %iam_resources.Properties.Tags[*].Value == /[a-zA-Z0-9]+/
        %iam_resources.Properties.Tags[*].Key   == /[a-zA-Z0-9]+/
    }
}"""
    assert _status(rules, IAM_TWO_ROLES) == "PASS"


def test_type_block_fails_on_untagged_resource():
    """eval_tests.rs rule_test_type_blocks: the AWS::IAM::Role type
    block evaluates per resource; the untagged one FAILs the file."""
    rules = """
rule iam_basic_checks {
  AWS::IAM::Role {
    Properties.AssumeRolePolicyDocument.Version == /(\\d{4})-(\\d{2})-(\\d{2})/
    Properties.PermissionsBoundary == /arn:aws:iam::(\\d{12}):policy/
    Properties.Tags[*].Value == /[a-zA-Z0-9]+/
    Properties.Tags[*].Key   == /[a-zA-Z0-9]+/
  }
}"""
    assert _status(rules, IAM_TWO_ROLES) == "FAIL"


def test_some_variable_selection_counts():
    """eval_tests.rs test_rules_with_some_clauses: `some` in a variable
    assignment drops unresolved entries; only the role whose Tag key
    matches the regex is selected."""
    rules = (
        "let x = some Resources.*[ Type == 'AWS::IAM::Role' ]"
        ".Properties.Tags[ Key == /[A-Za-z0-9]+Role/ ]\n"
        "rule has_x when %x !empty {\n    %x exists\n}\n"
    )
    doc = {
        "Resources": {
            "CounterTaskDefExecutionRole5959CB2D": {
                "Type": "AWS::IAM::Role",
                "Properties": {
                    "Tags": [{"Key": "TestRole", "Value": ""}],
                },
            },
            "BlankRole001": {
                "Type": "AWS::IAM::Role",
                "Properties": {"Tags": [{"Key": "FooBar", "Value": ""}]},
            },
            "BlankRole002": {
                "Type": "AWS::IAM::Role",
                "Properties": {},
            },
        }
    }
    rf = parse_rules_file(rules, "t.guard")
    scope = RootScope(rf, from_plain(doc))
    selected = scope.resolve_variable("x")
    resolved = [r for r in selected if getattr(r, "value", None) is not None]
    assert len(resolved) == 1
    assert _status(rules, doc, "has_x") == "PASS"


def test_map_keys_filter_function():
    """eval_tests.rs test_map_keys_function: `[ keys == /regex/ ]`
    selects map values by key name."""
    rules = """
let api_gw = Resources[ Type == 'AWS::ApiGateway::RestApi' ]
rule check_rest_api_is_private_and_has_access {
    %api_gw {
      Properties.EndpointConfiguration == ["PRIVATE"]
      some Properties.Policy.Statement[*].Condition[ keys == /aws:[sS]ource(Vpc|VPC|Vpce|VPCE)/ ] !empty
    }
}"""
    base = {
        "Resources": {
            "apiGw": {
                "Type": "AWS::ApiGateway::RestApi",
                "Properties": {
                    "EndpointConfiguration": ["PRIVATE"],
                    "Policy": {
                        "Statement": [
                            {
                                "Action": "Allow",
                                "Resource": ["*", "aws:"],
                                "Condition": {"aws:IsSecure": True},
                            }
                        ]
                    },
                },
            }
        }
    }
    assert _status(rules, base) == "FAIL"
    with_vpc = yaml.safe_load(yaml.safe_dump(base))
    with_vpc["Resources"]["apiGw"]["Properties"]["Policy"]["Statement"][0][
        "Condition"
    ]["aws:sourceVpc"] = ["vpc-1234"]
    assert _status(rules, with_vpc) == "PASS"


@pytest.mark.parametrize(
    "clause,expected",
    [
        ("Tags[*].Key == /Name/", "FAIL"),
        ("some Tags[*].Key == /Name/", "FAIL"),
        ("Tags[*] { Key == /Name/ }", "FAIL"),
        ("some Tags[*] { Key == /Name/ }", "FAIL"),
        ("Tags !empty", "FAIL"),
        ("Tags empty", "PASS"),
        ("Tags[*] !empty", "FAIL"),
        ("Tags[*] empty", "PASS"),
    ],
)
def test_all_list_value_access_on_empty(clause, expected):
    """eval_tests.rs ensure_all_list_value_access_on_empty_fails: every
    element access on an empty list is unresolved -> FAIL; emptiness
    checks PASS."""
    assert _clause_status(clause, {"Tags": []}) == expected


def test_rule_clause_tags_present_and_empty():
    """eval_tests.rs rule_clause_tests."""
    rules = """
rule check_all_resources_have_tags_present {
    let all_resources = Resources.*.Properties

    %all_resources.Tags EXISTS
    %all_resources.Tags !EMPTY
}"""
    tagged = {
        "Resources": {
            "vpc": {
                "Type": "AWS::EC2::VPC",
                "Properties": {
                    "CidrBlock": "10.0.0.0/25",
                    "Tags": [{"Key": "my-vpc", "Value": "my-vpc"}],
                },
            }
        }
    }
    assert _status(rules, tagged) == "PASS"
    untagged = {
        "Resources": {
            "vpc": {
                "Type": "AWS::EC2::VPC",
                "Properties": {"CidrBlock": "10.0.0.0/25", "Tags": []},
            }
        }
    }
    assert _status(rules, untagged) == "FAIL"


@pytest.mark.parametrize(
    "ttl_yaml,expected",
    [
        ("'900'", "PASS"),
        ("!!str 900", "PASS"),
        ("900", "FAIL"),
        ('!!int "900"', "FAIL"),
        ('!!float "900"', "FAIL"),
    ],
)
def test_type_conversions_no_coercion(ttl_yaml, expected):
    """eval_tests.rs test_type_conversions: YAML tags decide the node
    type and comparisons never coerce ("900" != 900)."""
    template = (
        "Resources:\n"
        "    MasterRecord:\n"
        "        Type: AWS::Route53::RecordSet\n"
        "        Properties:\n"
        f"            TTL: {ttl_yaml}\n"
    )
    doc = load_document(template, "t.yaml")
    rules = """
let aws_route53_recordset_resources = Resources.*[ Type == 'AWS::Route53::RecordSet' ]
rule aws_route53_recordset when %aws_route53_recordset_resources !empty {
  %aws_route53_recordset_resources.Properties.TTL == "900"
}"""
    assert _status(rules, doc) == expected


def test_double_projection_with_key_interpolation():
    """eval_tests.rs double_projection_tests: variable key interpolation
    (Resources.%iam_references) plus a filter over a variable's
    results."""
    rules = """
rule check_ecs_against_local_or_metadata {
    let ecs_tasks = Resources.*[
        Type == 'AWS::ECS::TaskDefinition'
        Properties.TaskRoleArn exists
    ]

    let iam_references = some %ecs_tasks.Properties.TaskRoleArn.'Fn::GetAtt'[0]
    when %iam_references !empty {
        let iam_local = Resources.%iam_references
        %iam_local.Type == 'AWS::IAM::Role'
        %iam_local.Properties.PermissionsBoundary exists
    }

    let ecs_task_role_is_string = %ecs_tasks[
        Properties.TaskRoleArn is_string
    ]
    when %ecs_task_role_is_string !empty {
        %ecs_task_role_is_string.Metadata.NotRestricted exists
    }
}"""
    passing = {
        "Resources": {
            "ecs": {
                "Type": "AWS::ECS::TaskDefinition",
                "Metadata": {"NotRestricted": True},
                "Properties": {"TaskRoleArn": "aws:arn..."},
            },
            "ecs2": {
                "Type": "AWS::ECS::TaskDefinition",
                "Properties": {"TaskRoleArn": {"Fn::GetAtt": ["iam", "arn"]}},
            },
            "iam": {
                "Type": "AWS::IAM::Role",
                "Properties": {"PermissionsBoundary": "aws:arn"},
            },
        }
    }
    assert _status(rules, passing) == "PASS"
    failing = {
        "Resources": {
            "ecs2": {
                "Type": "AWS::ECS::TaskDefinition",
                "Properties": {"TaskRoleArn": {"Fn::GetAtt": ["iam", "arn"]}},
            }
        }
    }
    assert _status(rules, failing) == "FAIL"


def test_is_bool_and_is_int_strictness():
    """eval_tests.rs is_bool / is_int."""
    assert _clause_status("foo is_bool", {"foo": False}) == "PASS"
    assert _clause_status("foo is_bool", {"foo": "false"}) == "FAIL"
    assert _clause_status("foo is_int", {"foo": 1}) == "PASS"
    assert _clause_status("foo is_int", {"foo": "1"}) == "FAIL"
