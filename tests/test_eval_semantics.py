"""Semantic cases ported from the reference's pinned evaluation suite
(`/root/reference/guard/src/rules/eval_tests.rs`) — each assertion
mirrors an upstream #[test] outcome."""

import yaml

from guard_tpu.core.evaluator import (
    eval_guard_clause,
    eval_rule,
    eval_rules_file,
)
from guard_tpu.core.loader import load_document, yaml_load_with_intrinsics
from guard_tpu.core.parser import Parser, parse_rules_file
from guard_tpu.core.qresult import Status
from guard_tpu.core.scopes import RootScope
from guard_tpu.core.values import from_plain


def clause_status(clause_str: str, doc: str) -> Status:
    rf = parse_rules_file(f"rule t0 {{\n{clause_str}\n}}\n", "")
    root = from_plain(yaml_load_with_intrinsics(doc))
    scope = RootScope(rf, root)
    return eval_rule(rf.guard_rules[0], scope)


def rule_status(rule_str: str, doc: str, rule_name=None) -> Status:
    rf = parse_rules_file(rule_str, "")
    root = from_plain(yaml_load_with_intrinsics(doc))
    scope = RootScope(rf, root)
    if rule_name:
        return scope.rule_status(rule_name)
    return eval_rules_file(rf, scope, None)


def test_field_type_array_or_single():
    """eval_tests.rs:1548-1605."""
    doc = """
    Statement:
      - Action: '*'
        Effect: Allow
        Resources: '*'
      - Action: ['api:Get', 'api2:Set']
        Effect: Allow
        Resources: '*'
    """
    assert clause_status("Statement[*].Action != '*'", doc) == Status.FAIL
    single = """
    Statement:
      Action: '*'
      Effect: Allow
      Resources: '*'
    """
    assert clause_status("Statement[*].Action != '*'", single) == Status.FAIL
    assert clause_status("Statement[*].Action[*] != '*'", single) == Status.FAIL
    assert clause_status("Statement.*.Action.* != '*'", single) == Status.FAIL
    # NB: upstream evaluates the `some` variants against the single-
    # statement document (the scope is reused, eval_tests.rs:1570-1601)
    assert clause_status("some Statement[*].Action == '*'", single) == Status.PASS
    assert clause_status("some Statement[*].Action != '*'", single) == Status.FAIL


def test_for_in_and_not_in():
    """eval_tests.rs:1607-1646."""
    doc = """
    mainSteps:
      - action: "aws:updateAgent"
      - action: "aws:configurePackage"
    """
    assert (
        clause_status(
            'mainSteps[*].action !IN ["aws:updateSsmAgent", "aws:updateAgent"]', doc
        )
        == Status.FAIL
    )
    assert (
        clause_status(
            'mainSteps[*].action IN ["aws:updateSsmAgent", "aws:updateAgent"]', doc
        )
        == Status.FAIL
    )
    assert (
        clause_status(
            'some mainSteps[*].action IN ["aws:updateSsmAgent", "aws:updateAgent"]',
            doc,
        )
        == Status.PASS
    )


def test_rule_with_range_test_and_this():
    """eval_tests.rs:1648-1691."""
    rule = (
        "rule check_parameter_validity {\n"
        "  InputParameter.TcpBlockedPorts[*] {\n"
        "    this in r[0, 65535] <<[NON_COMPLIANT] invalid>>\n"
        "  }\n"
        "}\n"
    )
    ok = "InputParameter:\n  TcpBlockedPorts:\n    - 21\n    - 22\n    - 101\n"
    assert rule_status(rule, ok, "check_parameter_validity") == Status.PASS
    bad = ok + "    - 100000\n"
    assert rule_status(rule, bad, "check_parameter_validity") == Status.FAIL


def test_inner_when_skipped():
    """eval_tests.rs:1692-1784."""
    rule = (
        "rule no_wild_card_in_managed_policy {\n"
        "  Resources[ Type == /ManagedPolicy/ ] {\n"
        "    when Properties.ManagedPolicyName != /Admin/ {\n"
        "      Properties.PolicyDocument.Statement[*].Action[*] != '*'\n"
        "    }\n"
        "  }\n"
        "}\n"
    )
    both = """
    Resources:
      ReadOnlyAdminPolicy:
        Type: 'AWS::IAM::ManagedPolicy'
        Properties:
          PolicyDocument:
            Statement:
              - Action: '*'
                Effect: Allow
                Resource: '*'
          ManagedPolicyName: AdminPolicy
      ReadOnlyPolicy:
        Type: 'AWS::IAM::ManagedPolicy'
        Properties:
          PolicyDocument:
            Statement:
              - Action: ['cloudwatch:*', '*']
                Effect: Allow
                Resource: '*'
          ManagedPolicyName: OperatorPolicy
    """
    assert rule_status(rule, both, "no_wild_card_in_managed_policy") == Status.FAIL
    admin_only = """
    Resources:
      ReadOnlyAdminPolicy:
        Type: 'AWS::IAM::ManagedPolicy'
        Properties:
          PolicyDocument:
            Statement:
              - Action: '*'
                Effect: Allow
                Resource: '*'
          ManagedPolicyName: AdminPolicy
    """
    assert rule_status(rule, admin_only, "no_wild_card_in_managed_policy") == Status.SKIP
    assert rule_status(rule, "Resources: {}\n", "no_wild_card_in_managed_policy") == Status.SKIP
    assert rule_status(rule, "{}", "no_wild_card_in_managed_policy") == Status.FAIL


def test_support_for_atleast_one_match_clause():
    """eval_tests.rs:2199-2293."""
    doc = """
    Tags:
      - Key: "InPROD"
        Value: "ProdApp"
      - Key: "NoP"
        Value: "NoQ"
    """
    assert clause_status("some Tags[*].Key == /PROD/", doc) == Status.PASS
    assert clause_status("Tags[*].Key == /PROD/", doc) == Status.FAIL
    empty_tags = "Tags: []\n"
    assert clause_status("some Tags[*].Key == /PROD/", empty_tags) == Status.FAIL
    assert clause_status("Tags[*].Key == /PROD/", empty_tags) == Status.FAIL
    assert clause_status("some Tags[*].Key == /PROD/", "{}") == Status.FAIL
    assert clause_status("Tags[*].Key == /PROD/", "{}") == Status.FAIL


def test_some_clause_variable_selection():
    """eval_tests.rs:2121-2196: `some` on a variable assignment drops
    unresolved entries."""
    rules = (
        "let x = some Resources.*[ Type == 'AWS::IAM::Role' ]"
        ".Properties.Tags[ Key == /[A-Za-z0-9]+Role/ ]\n"
        "rule uses_x {\n  %x !empty\n}\n"
    )
    doc = {
        "Resources": {
            "WithMatchingTag": {
                "Type": "AWS::IAM::Role",
                "Properties": {"Tags": [{"Key": "TestRole", "Value": ""}]},
            },
            "WithOtherTag": {
                "Type": "AWS::IAM::Role",
                "Properties": {"Tags": [{"Key": "FooBar", "Value": ""}]},
            },
            "NoTags": {"Type": "AWS::IAM::Role", "Properties": {}},
        }
    }
    rf = parse_rules_file(rules, "")
    scope = RootScope(rf, from_plain(doc))
    selected = scope.resolve_variable("x")
    assert len(selected) == 1


def test_in_comparison_for_list_of_lists():
    """eval_tests.rs:1895-1943 (parameterized cases)."""
    rules = """
    let aws_route53_recordset_resources = Resources.*[ Type == 'AWS::Route53::RecordSet' ]
    rule aws_route53_recordset when %aws_route53_recordset_resources !empty {
      let targets = [{"Fn::Join": ["",[{"Ref": "SubdomainMaster"},".", {"Ref": "HostedZoneName"}]]}, {"Fn::Join": ["",[{"Ref": "SubdomainWild"},".", {"Ref": "HostedZoneName"}]]}]
      %aws_route53_recordset_resources.Properties.Comment == "DNS name for my instance."
      %aws_route53_recordset_resources.Properties.ResourceRecords IN [[{"Fn::GetAtt": "Master.PrivateIp"}], [{"Fn::GetAtt": "Infra1.PrivateIp"}]]
      %aws_route53_recordset_resources.Properties.Name IN %targets
      %aws_route53_recordset_resources.Properties.Type == "A"
    }
    """

    def template(name, records):
        return {
            "Resources": {
                "MasterRecord": {
                    "Type": "AWS::Route53::RecordSet",
                    "Properties": {
                        "HostedZoneName": {"Ref": "HostedZoneName"},
                        "Comment": "DNS name for my instance.",
                        "Name": {
                            "Fn::Join": [
                                "",
                                [{"Ref": name}, ".", {"Ref": "HostedZoneName"}],
                            ]
                        },
                        "Type": "A",
                        "TTL": "900",
                        "ResourceRecords": [{"Fn::GetAtt": records}],
                    },
                }
            }
        }

    rf = parse_rules_file(rules, "")

    def status(name, records):
        scope = RootScope(rf, from_plain(template(name, records)))
        return eval_rules_file(rf, scope, None)

    assert status("SubdomainMaster", "Master.PrivateIp") == Status.PASS
    assert status("SubdomainWild", "Infra1.PrivateIp") == Status.PASS
    assert status("SubdomainMaster", "Unknown.PrivateIp") == Status.FAIL
    assert status("SubdomainUnknown", "Master.PrivateIp") == Status.FAIL


def test_string_in_comparison_with_capture():
    """eval_tests.rs:3958-3994 — upstream marks this #[ignore]: the live
    engine's query-to-query IN uses containment/equality only
    (operators.rs:406-447), which yields FAIL here. We pin the live
    behavior (captures still resolve, see the resolve assertion)."""
    rules = """
    let s3_buckets = Resources[ bucket_names | Type == 'AWS::S3::Bucket' ]
    rule s3_policies {
        when %s3_buckets not empty {
            Resources[ Type == 'AWS::S3::BucketPolicy' ] {
                some %bucket_names[*] in Properties.PolicyDocument.Statement.Resource.'Fn::Sub'
            }
        }
    }
    """
    doc = """
    Resources:
      s3:
        Type: AWS::S3::Bucket
      s3Policy:
        Type: AWS::S3::BucketPolicy
        Properties:
          PolicyDocument:
            Statement:
              Resource:
                Fn::Sub: "aws:arn:s3::${s3}"
    """
    rf = parse_rules_file(rules, "")
    root = from_plain(yaml_load_with_intrinsics(doc))
    scope = RootScope(rf, root)
    status = eval_rules_file(rf, scope, None)
    assert status == Status.FAIL  # live reference behavior (test ignored upstream)
    assert [q.value.val for q in scope.resolve_variable("bucket_names")] == ["s3"]


def test_yaml_scalar_type_eq():
    """eval_tests.rs:1945+ (type_conversions): '900' string literal only
    equals string-typed TTL values."""
    rules = "Resources.r.Properties.TTL == \"900\"\n"
    assert clause_status(rules.strip(), "Resources:\n  r:\n    Properties:\n      TTL: '900'\n") == Status.PASS
    assert clause_status(rules.strip(), "Resources:\n  r:\n    Properties:\n      TTL: 900\n") == Status.FAIL


def test_filter_scope_asymmetry_star_vs_allindices():
    """Reference asymmetry: `.*` on a map re-scopes each value
    (accumulate_map wraps a ValueScope, eval_context.rs:216-229), so
    `.*[ filter ]` evaluates the filter against each candidate. `[*]`
    on a list does NOT re-scope (accumulate, eval_context.rs:142-178),
    so `[*][ filter ]` evaluates map candidates against the outer
    scope — the filter keys resolve from the query root, not the
    element. `list[ filter ]` directly after a key iterates elements
    each in its own scope (the Filter-on-List branch)."""
    from guard_tpu.core.parser import parse_rules_file
    from guard_tpu.core.scopes import RootScope
    from guard_tpu.core.values import from_plain

    doc = from_plain(
        {
            "Resources": {
                "a": {"Type": "T1"},
                "b": {"Type": "T2"},
            },
            "Items": [{"Kind": "x"}, {"Kind": "y"}],
        }
    )

    # .*[ filter ]: candidate-scoped -> selects resource a only
    rules = "rule r { Resources.*[ Type == 'T1' ] !empty }"
    rf = parse_rules_file(rules, "t.guard")
    assert RootScope(rf, doc).rule_status("r").value == "PASS"

    # list[ filter ] after a key: element-scoped -> selects {Kind: x}
    rules = "rule r { Items[ Kind == 'x' ] !empty }"
    rf = parse_rules_file(rules, "t.guard")
    assert RootScope(rf, doc).rule_status("r").value == "PASS"

    # list[*][ filter ]: outer-scoped for map candidates -> `Kind`
    # resolves from the ROOT (missing) -> no candidate selected
    rules = "rule r { Items[*][ Kind == 'x' ] !empty }"
    rf = parse_rules_file(rules, "t.guard")
    assert RootScope(rf, doc).rule_status("r").value == "FAIL"

    # ...and the TPU lowering refuses the outer-scope construct
    from guard_tpu.ops.encoder import encode_batch
    from guard_tpu.ops.ir import compile_rules_file

    batch, interner = encode_batch([doc])
    compiled = compile_rules_file(rf, interner)
    assert not compiled.rules and len(compiled.host_rules) == 1
