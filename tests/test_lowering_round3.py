"""Differential tests for the round-3 lowering batch (VERDICT item 3):
struct literals with exact compare_eq tri-state columns (`!=` against
map literals, regex/range members), list-vs-list IN decided on device,
negated Eq against root-bound RHS inside value scopes, and function
lets / inline calls in when blocks. Every case must lower (no host
fallback unless stated) and match the CPU oracle bit-for-bit."""

from test_lowering_round2 import _differential


# ---------------------------------------------------------------------------
# struct literals: != / not, NotComparable propagation, short-circuit
# ---------------------------------------------------------------------------
def test_neq_map_literal_tri_state():
    # compare_eq(doc, lit) raising keeps FAIL through the inversion;
    # plain False inverts to PASS (operators.rs:195-206)
    rules = 'rule r { x != {"a": 1} }'
    docs = [
        {"x": {"a": 1}},          # equal -> FAIL
        {"x": {"a": 2}},          # unequal -> PASS
        {"x": "str"},             # STRING vs MAP raises -> FAIL
        {"x": {"a": 1.0}},        # INT-vs-FLOAT member raises -> FAIL
        {"x": {"b": 1}},          # missing key -> False -> PASS
        {"x": {"a": 1, "b": 2}},  # size mismatch -> False -> PASS
    ]
    _differential(rules, docs)


def test_neq_map_literal_short_circuit_order():
    # iteration follows DOC insertion order (values.compare_eq:391):
    # a False entry before a raising one returns False (PASS under !=);
    # a raising entry hit first keeps FAIL
    rules = 'rule r { x != {"a": 1, "b": "x"} }'
    docs = [
        {"x": {"a": "s", "b": 5}},   # 'a' raises first -> FAIL
        {"x": {"b": "y", "a": "s"}}, # 'b' False first -> PASS
        {"x": {"a": 1, "b": "x"}},   # equal -> FAIL
    ]
    _differential(rules, docs)


def test_eq_map_literal_regex_member():
    rules = 'rule r { x == {"name": /^prod/} }'
    docs = [
        {"x": {"name": "prod-1"}},
        {"x": {"name": "dev-1"}},
        {"x": {"name": 4}},  # INT vs REGEX raises -> FAIL
    ]
    _differential(rules, docs)


def test_eq_map_literal_range_member():
    rules = 'rule r { x == {"n": r(1, 5]} }'
    docs = [
        {"x": {"n": 3}},
        {"x": {"n": 1}},   # exclusive lower bound -> False
        {"x": {"n": 5}},   # inclusive upper bound -> True
        {"x": {"n": 99}},
    ]
    _differential(rules, docs)


def test_in_list_of_maps_with_regex_member():
    # IN membership is loose_eq: maps compare values order-insensitively
    # and regex members match (MapValue PartialEq -> loose_eq)
    rules = 'rule r { x IN [{"k": /v/}, {"k": "w"}] }'
    docs = [
        {"x": {"k": "value"}},
        {"x": {"k": "w"}},
        {"x": {"k": "zzz"}},
        {"x": 3},
    ]
    _differential(rules, docs)


def test_not_in_list_of_maps():
    rules = 'rule r { x not IN [{"a": 1}] }'
    docs = [
        {"x": {"a": 1}},
        {"x": {"a": 2}},
        {"x": "s"},
    ]
    _differential(rules, docs)


def test_neq_list_literal_with_struct_item():
    # ordered elementwise compare with short-circuit NotComparable
    rules = 'rule r { x != [{"a": 1}, 2] }'
    docs = [
        {"x": [{"a": 1}, 2]},    # equal -> FAIL
        {"x": [{"a": 1}, 3]},    # second unequal -> PASS
        {"x": [{"a": "s"}, 2]},  # first member False (not raise) -> PASS
        {"x": [3, 2]},           # INT vs MAP raises at item 0 -> FAIL
        {"x": [{"a": 1}]},       # length mismatch -> PASS
    ]
    _differential(rules, docs)


def test_in_scalar_map_rhs_compare_eq():
    # `x IN {map}` goes through _match_value(compare_eq): raising pairs
    # keep FAIL under not in
    rules = (
        'rule r { x IN {"a": 1} }\n'
        'rule s { x not IN {"a": 1} }'
    )
    docs = [
        {"x": {"a": 1}},
        {"x": {"a": 2}},
        {"x": "s"},  # raises: FAIL both rules
    ]
    _differential(rules, docs)


def test_ordering_vs_map_literal_not_comparable():
    rules = 'rule r { x > {"a": 1} }'
    docs = [{"x": 5}, {"x": {"a": 1}}]
    _differential(rules, docs)


def test_map_literal_nested_struct_members():
    rules = 'rule r { x == {"outer": {"inner": [1, 2]}} }\n' \
            'rule s { x != {"outer": {"inner": [1, 2]}} }'
    docs = [
        {"x": {"outer": {"inner": [1, 2]}}},
        {"x": {"outer": {"inner": [1, 2, 3]}}},
        {"x": {"outer": {"inner": [1, 2.0]}}},  # nested raise
        {"x": {"outer": "flat"}},
    ]
    _differential(rules, docs)


# ---------------------------------------------------------------------------
# list-vs-list IN between query results, decided on device
# ---------------------------------------------------------------------------
def test_list_in_list_subset_mode():
    # rhs first element is a scalar: subset-of-elements semantics
    rules = "rule r { x IN y }"
    docs = [
        {"x": [1, 2], "y": [1, 2, 3]},      # subset -> PASS
        {"x": [1, 9], "y": [1, 2, 3]},      # 9 missing -> FAIL
        {"x": [], "y": [1]},                # vacuous subset -> PASS
        {"x": [1, 1], "y": [1]},            # duplicates still subset
    ]
    _differential(rules, docs)


def test_list_in_list_membership_mode():
    # rhs first element is itself a list: whole-list membership, and
    # identity does NOT imply containment
    rules = "rule r { x IN y }"
    docs = [
        {"x": [1, 2], "y": [[1, 2], [3]]},   # member -> PASS
        {"x": [1, 2], "y": [[1], [2]]},      # not a member -> FAIL
        {"x": [3], "y": [[1, 2], [3]]},      # member -> PASS
        # mixed rhs: first element list decides the mode
        {"x": [5], "y": [[5], 5]},           # membership: [5] in rhs -> PASS
    ]
    _differential(rules, docs)


def test_list_not_in_list():
    rules = "rule r { x not IN y }"
    docs = [
        {"x": [1, 2], "y": [1, 2, 3]},
        {"x": [1, 9], "y": [1, 2, 3]},
        {"x": [1, 2], "y": [[1, 2]]},
        {"x": [1, 2], "y": [[1], [2]]},
    ]
    _differential(rules, docs)


def test_scalar_in_empty_and_nested_lists():
    rules = "rule r { x IN y }"
    docs = [
        {"x": "a", "y": ["a", "b"]},
        {"x": "z", "y": ["a", "b"]},
        {"x": [1], "y": []},                  # subset mode, diff=[1] -> FAIL
        {"x": [], "y": []},                   # vacuous -> PASS
        {"x": {"k": 1}, "y": [{"k": 1}]},     # map membership
    ]
    _differential(rules, docs)


# ---------------------------------------------------------------------------
# negated Eq against a root-bound RHS inside a value scope
# ---------------------------------------------------------------------------
def test_neq_root_variable_inside_filter():
    rules = """
let allowed = Parameters.Zones

rule r {
    Resources.*[ Properties.Zone != %allowed ] empty
}
"""
    docs = [
        {"Parameters": {"Zones": ["us-1"]},
         "Resources": {"a": {"Properties": {"Zone": "us-1"}}}},
        {"Parameters": {"Zones": ["us-1"]},
         "Resources": {"a": {"Properties": {"Zone": "eu-9"}}}},
        {"Parameters": {"Zones": ["us-1", "us-2"]},
         "Resources": {"a": {"Properties": {"Zone": "us-1"}},
                       "b": {"Properties": {"Zone": "us-2"}}}},
        # multi-value LHS per origin vs larger shared RHS
        {"Parameters": {"Zones": ["us-1", "us-2", "us-3"]},
         "Resources": {"a": {"Properties": {"Zone": ["us-1", "us-2"]}}}},
    ]
    _differential(rules, docs)


def test_neq_root_variable_inside_block():
    rules = """
let expected = Parameters.Expected

rule r {
    Resources.* {
        Properties.Tag != %expected
    }
}
"""
    docs = [
        {"Parameters": {"Expected": "prod"},
         "Resources": {"a": {"Properties": {"Tag": "prod"}},
                       "b": {"Properties": {"Tag": "dev"}}}},
        {"Parameters": {"Expected": "prod"},
         "Resources": {"a": {"Properties": {"Tag": "dev"}}}},
        # NotComparable stays FAIL through the inversion
        {"Parameters": {"Expected": "prod"},
         "Resources": {"a": {"Properties": {"Tag": 5}}}},
    ]
    _differential(rules, docs)


def test_neq_function_rhs_inside_block():
    # inline call in a NESTED clause: precomputable because every
    # query argument is headed by a root-bound variable
    rules = """
let sep = Parameters.Sep
let parts = Parameters.Parts[*]

rule r {
    Resources.* {
        Properties.Joined != join(%parts, %sep)
    }
}
"""
    docs = [
        {"Parameters": {"Sep": ",", "Parts": ["a", "b"]},
         "Resources": {"x": {"Properties": {"Joined": "a,b"}}}},
        {"Parameters": {"Sep": ",", "Parts": ["a", "b"]},
         "Resources": {"x": {"Properties": {"Joined": "a-b"}}}},
    ]
    _differential(rules, docs)


def test_inline_call_inside_filter():
    rules = """
let pre = Parameters.Prefix

rule r {
    Resources.*[ Properties.Name == to_upper(%pre) ] !empty
}
"""
    docs = [
        {"Parameters": {"Prefix": "app"},
         "Resources": {"a": {"Properties": {"Name": "APP"}}}},
        {"Parameters": {"Prefix": "app"},
         "Resources": {"a": {"Properties": {"Name": "app"}}}},
    ]
    _differential(rules, docs)


# ---------------------------------------------------------------------------
# function lets and inline calls inside when blocks (root basis)
# ---------------------------------------------------------------------------
def test_function_let_inside_when_block():
    rules = """
rule r {
    when Parameters.Env exists {
        let upper_env = to_upper(Parameters.Env)
        Resources.Tag == %upper_env
    }
}
"""
    docs = [
        {"Parameters": {"Env": "prod"}, "Resources": {"Tag": "PROD"}},
        {"Parameters": {"Env": "prod"}, "Resources": {"Tag": "prod"}},
        {"Resources": {"Tag": "PROD"}},  # when-gate SKIPs
    ]
    _differential(rules, docs)


def test_function_let_in_nested_when_block_chained():
    # when-in-when keeps the root basis; the inner let chains through
    # the outer let
    rules = """
rule r {
    let base = Parameters.Name
    when %base exists {
        when Parameters.Mode == "strict" {
            let canon = to_lower(%base)
            Resources.Id == %canon
        }
    }
}
"""
    docs = [
        {"Parameters": {"Name": "AbC", "Mode": "strict"},
         "Resources": {"Id": "abc"}},
        {"Parameters": {"Name": "AbC", "Mode": "strict"},
         "Resources": {"Id": "AbC"}},
        {"Parameters": {"Name": "AbC", "Mode": "lax"},
         "Resources": {"Id": "abc"}},
    ]
    _differential(rules, docs)


def test_inline_call_inside_when_block_clause():
    rules = """
rule r {
    when Parameters.Csv exists {
        Resources.Joined == join(Parameters.Parts[*], ",")
    }
}
"""
    docs = [
        {"Parameters": {"Csv": True, "Parts": ["x", "y"]},
         "Resources": {"Joined": "x,y"}},
        {"Parameters": {"Csv": True, "Parts": ["x", "y"]},
         "Resources": {"Joined": "x;y"}},
    ]
    _differential(rules, docs)


def test_duplicate_when_let_name_lowers():
    # two when blocks binding the same function-let name: round 5 keys
    # precompute slots on the binding's expression identity, so both
    # bindings lower and resolve through their own block chains
    rules = """
rule r {
    when Parameters.A exists {
        let v = to_upper(Parameters.A)
        Resources.X == %v
    }
    when Parameters.B exists {
        let v = to_lower(Parameters.B)
        Resources.Y == %v
    }
}
"""
    docs = [
        {"Parameters": {"A": "a", "B": "B"},
         "Resources": {"X": "A", "Y": "b"}},
        {"Parameters": {"A": "a"}, "Resources": {"X": "nope", "Y": "b"}},
        {"Parameters": {"B": "Q"}, "Resources": {"X": "A", "Y": "q"}},
    ]
    _differential(rules, docs, expect_host=0)


# ---------------------------------------------------------------------------
# folded key chains (ir.StepKeyChain): adversarial nesting
# ---------------------------------------------------------------------------
def test_chain_fold_self_similar_paths():
    # a.b chains over documents with nested a.b.a.b paths: the folded
    # anchor must pick the dynamically-selected basis only
    rules = (
        "rule r { a.b exists }\n"
        "rule s { a.b.c == 1 }\n"
        "rule t { some a.b.a exists }\n"
    )
    docs = [
        {"a": {"b": {"c": 1}}},
        {"a": {"b": {"a": {"b": {"c": 2}}}}},
        {"a": {"b": {"c": {"a": {"b": 1}}}}},
        {"a": {"c": 1}},
        {"b": {"a": {"b": 1}}},
        {"a": {"b": {"a": 5}}},
    ]
    _differential(rules, docs)


def test_chain_fold_miss_accounting():
    # deep misses at every position, mixed with full matches, must
    # reproduce the oracle's UnResolved counts (they gate some/all)
    rules = (
        "rule r { Resources.*.Properties.Enc.Alg == 'kms' }\n"
        "rule s { some Resources.*.Properties.Enc.Alg == 'kms' }\n"
    )
    docs = [
        {"Resources": {"a": {"Properties": {"Enc": {"Alg": "kms"}}},
                       "b": {"Properties": {"Enc": {}}}}},
        {"Resources": {"a": {"Properties": {}},
                       "b": {"Properties": {"Enc": {"Alg": "aes"}}}}},
        {"Resources": {"a": {"Other": 1}}},
        {"Resources": {"a": {"Properties": {"Enc": {"Alg": "kms"}},
                             "Extra": {"Properties": 1}}}},
    ]
    _differential(rules, docs)


def test_chain_fold_inside_filters_and_vars():
    rules = """
let plans = resource_changes[ change.actions[*] == 'create' ]

rule r when %plans !empty {
    %plans.change.after.acl != 'public-read'
    %plans.change.after.tags.env IN ['prod', 'dev']
}
"""
    docs = [
        {"resource_changes": [
            {"change": {"actions": ["create"],
                        "after": {"acl": "private",
                                  "tags": {"env": "prod"}}}},
        ]},
        {"resource_changes": [
            {"change": {"actions": ["create"],
                        "after": {"acl": "public-read",
                                  "tags": {"env": "qa"}}}},
            {"change": {"actions": ["update"]}},
        ]},
        {"resource_changes": []},
    ]
    _differential(rules, docs)
