"""Native C++ encoder: build, structural parity with the Python encoder,
and end-to-end evaluation parity."""

import json

import numpy as np
import pytest

from guard_tpu.core.loader import load_document
from guard_tpu.core.parser import parse_rules_file
from guard_tpu.ops.encoder import encode_batch
from guard_tpu.ops.ir import compile_rules_file
from guard_tpu.ops.kernels import evaluate_batch
from guard_tpu.ops.native_encoder import (
    build_native,
    encode_json_batch_native,
    native_available,
)

pytestmark = pytest.mark.skipif(
    not build_native(), reason="native toolchain unavailable"
)

DOCS = [
    json.dumps(
        {
            "Resources": {
                "b": {
                    "Type": "AWS::S3::Bucket",
                    "Properties": {
                        "Size": 5,
                        "Rate": 1.5,
                        "On": True,
                        "Off": False,
                        "Nothing": None,
                        "L": [1, "two", {"k": "v"}],
                        "Esc": 'quote " and \\ slash\nnewline',
                    },
                }
            }
        }
    ),
    '{"a": []}',
    "{}",
    '{"unicode": "\\u00e9\\u0041"}',
]


def test_native_matches_python_structure():
    batch_n, interner_n, err = encode_json_batch_native(DOCS)
    assert err is None
    batch_p, interner_p = encode_batch([load_document(d) for d in DOCS])
    assert set(interner_n.strings) == set(interner_p.strings)
    assert batch_n.node_kind.shape == batch_p.node_kind.shape
    for k, a in batch_p.arrays().items():
        b = batch_n.arrays()[k]
        if k in ("scalar_id", "edge_key_id"):
            # intern order may differ; compare presence masks
            assert np.array_equal(a >= 0, b >= 0), k
        else:
            assert np.array_equal(a, b), k


def test_native_eval_parity():
    rules = parse_rules_file(
        "Resources.*[ Type == 'AWS::S3::Bucket' ] {\n"
        "  Properties.Size == 5\n"
        "  Properties.On == true\n"
        "}\n",
        "",
    )
    batch_n, interner_n, _ = encode_json_batch_native(DOCS)
    batch_p, interner_p = encode_batch([load_document(d) for d in DOCS])
    s_n = evaluate_batch(compile_rules_file(rules, interner_n), batch_n)
    s_p = evaluate_batch(compile_rules_file(rules, interner_p), batch_p)
    assert np.array_equal(s_n, s_p)


def test_native_reports_bad_doc():
    _batch, _interner, err = encode_json_batch_native(['{"ok": 1}', "{bad", "{}"])
    assert err == 1
