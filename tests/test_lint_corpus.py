"""The shipped rule corpora are lint-clean: zero ERROR findings over
corpus/rules and examples/, pinned so the linter's conservative
analysis can never rot into false positives on real rule sets — and
so a future corpus addition with a genuinely unsatisfiable rule fails
CI here instead of shipping dead rules.

(The synthetic corpus intentionally reuses rule names across files —
its variant generator stamps `_v1`/`_v2` families — so INFO-level
cross-file-duplicate findings are expected and allowed; anything
ERROR or WARNING is not.)
"""

from pathlib import Path

import pytest

from guard_tpu.cli import run
from guard_tpu.commands.lint import lint_findings
from guard_tpu.utils.io import Reader, Writer

REPO = Path(__file__).resolve().parent.parent

CORPORA = [p for p in (REPO / "corpus" / "rules", REPO / "examples")
           if p.is_dir()]


@pytest.mark.parametrize("corpus", CORPORA, ids=lambda p: p.name)
def test_corpus_has_no_error_or_warning_findings(corpus):
    findings = lint_findings([str(corpus)])
    loud = [f for f in findings if f.severity in ("ERROR", "WARNING")]
    assert loud == [], "\n".join(f.render() for f in loud)


def test_corpus_info_findings_are_only_cross_file_duplicates():
    findings = lint_findings([str(p) for p in CORPORA])
    assert all(f.code == "cross-file-duplicate" for f in findings), {
        f.code for f in findings
    }


def test_cli_over_shipped_corpora_exits_clean():
    w = Writer.buffered()
    rc = run(["lint", "-r", *[str(p) for p in CORPORA]], writer=w,
             reader=Reader())
    assert rc == 0
    assert "0 error(s), 0 warning(s)" in w.err.getvalue()
