"""Byte-level parity with the reference's validate golden outputs
(guard/tests/validate.rs + resources/validate/output-dir): console
summary with CFN-aware resource blocks, verbose event trees, structured
JSON/YAML, SARIF and JUnit. SARIF/JUnit apply the same sanitizations
the reference's own tests do (uri / time) plus tool-identity
neutralization (this framework reports its own name)."""

import pathlib
import re

import pytest

from guard_tpu.cli import run
from guard_tpu.utils.io import Reader, Writer

REF = pathlib.Path("/root/reference/guard/resources/validate")

needs_reference = pytest.mark.skipif(
    not REF.exists(), reason="reference checkout not available"
)


def _run(args, stdin: str = None):
    w = Writer.buffered()
    r = Reader.from_string(stdin) if stdin is not None else None
    code = run(args, writer=w, reader=r)
    return code, w.stripped()


def _golden(name: str) -> str:
    return (REF / "output-dir" / name).read_text()


CONSOLE_CASES = [
    (
        "rules_dir_against_data_dir.out",
        ["-r", str(REF / "rules-dir"), "-d", str(REF / "data-dir")],
        19,
    ),
    (
        "advanced_regex_negative_lookbehind_non_compliant.out",
        [
            "-r", str(REF / "rules-dir/advanced_regex_negative_lookbehind_rule.guard"),
            "-d", str(REF / "data-dir/advanced_regex_negative_lookbehind_non_compliant.yaml"),
            "--show-summary", "all",
        ],
        19,
    ),
    (
        "advanced_regex_negative_lookbehind_compliant.out",
        [
            "-r", str(REF / "rules-dir/advanced_regex_negative_lookbehind_rule.guard"),
            "-d", str(REF / "data-dir/advanced_regex_negative_lookbehind_compliant.yaml"),
            "--show-summary", "all",
        ],
        0,
    ),
    (
        "test_single_data_file_single_rules_file_verbose.out",
        [
            "-r", str(REF / "rules-dir/s3_bucket_public_read_prohibited.guard"),
            "-d", str(REF / "data-dir/s3-public-read-prohibited-template-non-compliant.yaml"),
            "--show-summary", "all",
        ],
        19,
    ),
    (
        "test_single_data_file_single_rules_file_verbose_compliant.out",
        [
            "-r", str(REF / "rules-dir/s3_bucket_public_read_prohibited.guard"),
            "-d", str(REF / "data-dir/s3-public-read-prohibited-template-compliant.yaml"),
            "--show-summary", "all", "--verbose",
        ],
        0,
    ),
    (
        "test_single_data_file_single_rules_file_verbose_non_compliant.out",
        [
            "-r", str(REF / "rules-dir/s3_bucket_public_read_prohibited.guard"),
            "-d", str(REF / "data-dir/s3-public-read-prohibited-template-non-compliant.yaml"),
            "--show-summary", "all", "--verbose",
        ],
        19,
    ),
    (
        "failing_template_without_resources_at_root.out",
        [
            "-r", str(REF / "workshop.guard"),
            "-d", str(REF / "template_where_resources_isnt_root.json"),
            "--show-summary", "all", "--verbose",
        ],
        19,
    ),
    (
        "failing_template_with_slash_in_key.out",
        [
            "-r", str(REF / "rules-dir/s3_bucket_server_side_encryption_enabled.guard"),
            "-d", str(REF / "failing_template_with_slash_in_key.yaml"),
            "--show-summary", "all", "--verbose",
        ],
        19,
    ),
]


@needs_reference
@pytest.mark.parametrize(
    "golden,args,expected_code",
    CONSOLE_CASES,
    ids=[c[0] for c in CONSOLE_CASES],
)
def test_console_goldens(golden, args, expected_code):
    code, out = _run(["validate"] + args)
    assert code == expected_code
    assert out == _golden(golden)


STRUCTURED_ARGS = [
    "validate",
    "-r", str(REF / "rules-dir"),
    "-d", str(REF / "data-dir/s3-public-read-prohibited-template-non-compliant.yaml"),
    "--show-summary", "none", "--structured", "-o",
]


@needs_reference
@pytest.mark.parametrize("fmt", ["json", "yaml"])
def test_structured_goldens(fmt):
    code, out = _run(STRUCTURED_ARGS + [fmt])
    assert code == 19
    assert out == _golden(f"structured.{fmt}")


@needs_reference
def test_sarif_golden():
    code, out = _run(STRUCTURED_ARGS + ["sarif"])
    assert code == 19

    def sanitize(t):
        # same uri sanitization as the reference's own tests
        # (tests/utils.rs:82-91) plus tool-identity neutralization
        t = re.sub(r'"uri": ".*"', '"uri": "some/path"', t)
        t = re.sub(
            r'"(name|semanticVersion|fullName|organization|downloadUri|'
            r'informationUri)": ".*"',
            '"id": "x"',
            t,
        )
        t = re.sub(
            r'"text": "(AWS CloudFormation Guard|guard-tpu) is an open-source.*"',
            '"text": "d"',
            t,
        )
        return t

    assert sanitize(out) == sanitize(_golden("structured.sarif"))


@needs_reference
def test_junit_golden():
    code, out = _run(STRUCTURED_ARGS + ["junit"])
    assert code == 19

    def sanitize(t):
        # tests/utils.rs:70-79 time sanitization + tool name
        t = re.sub(r'time="[^"]*"', 'time="0"', t)
        return t.replace("guard-tpu validate report", "cfn-guard validate report")

    assert sanitize(out) == sanitize(_golden("structured.junit"))


@needs_reference
def test_stdin_payload_verbose_goldens():
    data = (REF / "data-dir/s3-public-read-prohibited-template-compliant.yaml").read_text()
    rules = str(REF / "rules-dir/s3_bucket_public_read_prohibited.guard")
    code, out = _run(["validate", "-r", rules, "--verbose"], stdin=data)
    assert code == 0
    assert out == _golden("payload_verbose_success.out")
    code, out = _run(["validate", "-r", rules, "--verbose", "-o", "yaml"], stdin=data)
    assert code == 0
    assert out == _golden("payload_verbose_yaml_compliant.out")
    data_nc = (REF / "data-dir/s3-public-read-prohibited-template-non-compliant.yaml").read_text()
    code, out = _run(["validate", "-r", rules, "--verbose"], stdin=data_nc)
    assert code == 19
    assert out == _golden("payload_verbose_non_compliant.out")


TEST_REF = pathlib.Path("/root/reference/guard/resources")


def _run_in_ref(args, cwd=None):
    """test-command goldens embed paths relative to the reference's
    guard/ directory, so run with that cwd."""
    import os

    prev = os.getcwd()
    os.chdir(cwd or str(TEST_REF.parent))
    try:
        return _run(args)
    finally:
        os.chdir(prev)


TEST_CONSOLE_CASES = [
    (
        "test_data_file.out",
        ["-r", "resources/validate/rules-dir/s3_bucket_server_side_encryption_enabled.guard",
         "-t", "resources/test-command/data-dir/s3_bucket_server_side_encryption_enabled.json"],
    ),
    (
        "test_data_file_with_shorthand_reference.out",
        ["-r", "resources/validate/rules-dir/s3_bucket_server_side_encryption_enabled.guard",
         "-t", "resources/test-command/data-dir/s3_bucket_logging_enabled_tests.json"],
    ),
    (
        "test_data_file_verbose.out",
        ["-r", "resources/validate/rules-dir/s3_bucket_server_side_encryption_enabled.guard",
         "-t", "resources/test-command/data-dir/s3_bucket_server_side_encryption_enabled.json",
         "--verbose"],
    ),
    ("test_data_dir_verbose.out", ["-d", "resources/test-command/dir", "--verbose"]),
    (
        "functions.out",
        ["-r", "resources/test-command/functions/rules/json_parse.guard",
         "-t", "resources/test-command/functions/data/template.yaml"],
    ),
    (
        "structured_single_report_json.out",
        ["-r", "resources/validate/rules-dir/s3_bucket_server_side_encryption_enabled.guard",
         "-t", "resources/test-command/data-dir/s3_bucket_server_side_encryption_enabled.json",
         "-o", "json"],
    ),
    (
        "structured_single_report_yaml.out",
        ["-r", "resources/validate/rules-dir/s3_bucket_server_side_encryption_enabled.guard",
         "-t", "resources/test-command/data-dir/s3_bucket_server_side_encryption_enabled.json",
         "-o", "yaml"],
    ),
    ("structured_directory_report_json.out", ["-d", "resources/test-command/dir", "-o", "json"]),
    ("structured_directory_report_yaml.out", ["-d", "resources/test-command/dir", "-o", "yaml"]),
]


@needs_reference
@pytest.mark.parametrize(
    "golden,args", TEST_CONSOLE_CASES, ids=[c[0] for c in TEST_CONSOLE_CASES]
)
def test_test_command_goldens(golden, args):
    code, out = _run_in_ref(["test"] + args)
    assert code == 0
    assert out == (TEST_REF / "test-command/output-dir" / golden).read_text()


@needs_reference
@pytest.mark.parametrize("mode", ["single", "directory"])
def test_test_command_junit_goldens(mode):
    if mode == "single":
        args = ["-r", "resources/validate/rules-dir/s3_bucket_server_side_encryption_enabled.guard",
                "-t", "resources/test-command/data-dir/s3_bucket_server_side_encryption_enabled.json"]
    else:
        args = ["-d", "resources/test-command/dir"]
    code, out = _run_in_ref(["test"] + args + ["-o", "junit"])
    assert code == 0

    def sanitize(t):
        t = re.sub(r'time="[^"]*"', 'time="0"', t)
        return t.replace("guard-tpu", "cfn-guard")

    gold = (TEST_REF / f"test-command/output-dir/structured_{mode}_report_junit.out").read_text()
    assert sanitize(out) == sanitize(gold)


PARSE_TREE_CASES = [
    ("parse-tree/rules-dir/rule_with_this_keyword.guard",
     "parse-tree/output-dir/test_rule_with_this_keyword.yaml", []),
    ("parse-tree/rules-dir/iterate_through_json_list_without_key.guard",
     "parse-tree/output-dir/test_rule_iterate_through_json_list_without_key.yaml", []),
    ("validate/functions/rules/string_manipulation.guard",
     "parse-tree/output-dir/parse_tree_functions.yaml", []),
    ("validate/rules-dir/s3_bucket_server_side_encryption_enabled.guard",
     "parse-tree/output-dir/s3_bucket_server_side_encryption_parse_tree.json",
     ["--print-json"]),
]


@needs_reference
@pytest.mark.parametrize(
    "rules,golden,extra", PARSE_TREE_CASES, ids=[c[1].split("/")[-1] for c in PARSE_TREE_CASES]
)
def test_parse_tree_goldens(rules, golden, extra):
    code, out = _run(["parse-tree", "-r", str(TEST_REF / rules)] + extra)
    assert code == 0
    assert out == (TEST_REF / golden).read_text()


@needs_reference
def test_structured_payload_golden():
    """validate.rs test_structured_output_payload: stdin payload with
    --structured -o json, pinned to structured-payload.json. The
    payload is extracted from the reference test source at run time."""
    src = pathlib.Path("/root/reference/guard/tests/validate.rs").read_text()
    m = re.search(r'const COMPLIANT_PAYLOAD: &str = r#"(.*?)"#;', src, re.S)
    payload = m.group(1)
    code, out = _run(
        ["validate", "--payload", "--structured", "-o", "json",
         "--show-summary", "none"],
        stdin=payload,
    )
    assert code == 0
    assert out == _golden("structured-payload.json")
