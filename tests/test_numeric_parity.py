"""Exact numeric parity between the device kernels and the CPU oracle.

The device carries every number as an order-preserving (hi, lo) int32
key pair (encoder.num_key): exact for all i64 integers and the full f64
total order — no float32 collisions (VERDICT round 1, item 3; reference
compares native i64/f64, path_value.rs:1071-1191). Values with no exact
encoding (NaN, beyond-i64 ints) flag the document and are never decided
on device.
"""

import numpy as np
import pytest

from guard_tpu.core.parser import parse_rules_file
from guard_tpu.core.scopes import RootScope
from guard_tpu.core.evaluator import eval_rules_file
from guard_tpu.core.values import FLOAT, INT, from_plain
from guard_tpu.ops.encoder import encode_batch, num_key, split_batch_by_size
from guard_tpu.ops.ir import compile_rules_file
from guard_tpu.ops.kernels import BatchEvaluator

STATUS = {0: "PASS", 1: "FAIL", 2: "SKIP"}


def _oracle_statuses(rf, doc):
    scope = RootScope(rf, doc)
    eval_rules_file(rf, scope, None)
    from guard_tpu.commands.report import rule_statuses_from_root

    root = scope.reset_recorder().extract()
    return {n: s.value for n, s in rule_statuses_from_root(root).items()}


def _differential(rules_text, docs_plain):
    rf = parse_rules_file(rules_text, "num.guard")
    docs = [from_plain(d) for d in docs_plain]
    batch, interner = encode_batch(docs)
    compiled = compile_rules_file(rf, interner)
    assert not compiled.host_rules, "all rules must lower for this test"
    statuses = BatchEvaluator(compiled)(batch)
    for di, doc in enumerate(docs):
        oracle = _oracle_statuses(rf, doc)
        for ri, crule in enumerate(compiled.rules):
            dev = STATUS[int(statuses[di, ri])]
            assert dev == oracle[crule.name], (
                f"doc {di} ({docs_plain[di]}) rule {crule.name}: "
                f"device={dev} oracle={oracle[crule.name]}"
            )


def test_int_eq_beyond_f32_mantissa():
    # 16777216 and 16777217 collide in float32; 2^53±1 collide in f64
    _differential(
        """
rule eq_24 { v == 16777217 }
rule eq_53 { v == 9007199254740993 }
rule neq_53 { v != 9007199254740992 }
""",
        [
            {"v": 16777216},
            {"v": 16777217},
            {"v": 9007199254740992},
            {"v": 9007199254740993},
        ],
    )


def test_int_ordering_adjacent_large():
    _differential(
        """
rule gt { v > 9007199254740992 }
rule ge { v >= 9007199254740993 }
rule lt { v < 9007199254740993 }
rule le { v <= 9007199254740992 }
rule big_gt { v > 9223372036854775806 }
""",
        [
            {"v": 9007199254740992},
            {"v": 9007199254740993},
            {"v": 9223372036854775806},
            {"v": 9223372036854775807},
            {"v": -9223372036854775808},
        ],
    )


def test_int_range_large_bounds():
    _differential(
        """
rule in_range { v IN r[9007199254740993, 9223372036854775807] }
rule excl_range { v IN r(16777216, 16777218) }
""",
        [
            {"v": 9007199254740992},
            {"v": 9007199254740993},
            {"v": 9223372036854775807},
            {"v": 16777216},
            {"v": 16777217},
            {"v": 16777218},
        ],
    )


def test_float_exactness_and_order():
    _differential(
        """
rule tenth { v == 0.1 }
rule tiny_gt { v > 0.0 }
rule neg_zero { v == 0.0 }
rule huge { v >= 1.0e+308 }
""",
        [
            {"v": 0.1},
            {"v": 0.30000000000000004},
            {"v": 5e-324},
            {"v": -0.0},
            {"v": 0.0},
            {"v": 1.0e308},
            {"v": 1.7976931348623157e308},
            {"v": -1.0e-300},
        ],
    )


def test_exotic_ints_route_to_host():
    docs = [from_plain({"v": 1}), from_plain({"v": 2**63}), from_plain({"v": -(2**64)})]
    batch, _ = encode_batch(docs)
    assert batch.num_exotic.tolist() == [False, True, True]
    groups, oversize = split_batch_by_size(batch)
    assert set(int(i) for i in oversize) == {1, 2}
    grouped = {int(i) for _, idx in groups for i in idx}
    assert grouped == {0}


def test_num_key_total_order_random():
    rng = np.random.default_rng(3)
    ints = sorted(
        set(
            int(x)
            for x in np.concatenate(
                [
                    rng.integers(-(2**63), 2**63 - 1, 200, dtype=np.int64),
                    np.array([0, 1, -1, 2**24, 2**24 + 1, 2**53 - 1], np.int64),
                ]
            )
        )
    )
    keys = [num_key(INT, v) for v in ints]
    assert keys == sorted(keys) and len(set(keys)) == len(keys)
    floats = sorted(
        set(
            float(x)
            for x in np.concatenate(
                [
                    rng.standard_normal(200) * 10.0 ** rng.integers(-300, 300, 200),
                    np.array([0.0, 1.0, -1.0, 0.1, 1e308, -1e308]),
                ]
            )
        )
    )
    keys = [num_key(FLOAT, v) for v in floats]
    assert keys == sorted(keys) and len(set(keys)) == len(keys)


def test_backend_cli_parity_big_ints(tmp_path):
    """End-to-end: --backend tpu on a corpus with >2^24 ints must agree
    with the plain CPU path on exit code and per-rule outcome."""
    import json
    import subprocess
    import sys

    rules = tmp_path / "r.guard"
    rules.write_text(
        "rule big_eq { v == 9007199254740993 }\n"
        "rule big_lim { v <= 16777216 }\n"
    )
    data = tmp_path / "data"
    data.mkdir()
    for i, v in enumerate(
        [16777216, 16777217, 9007199254740992, 9007199254740993]
    ):
        (data / f"d{i}.json").write_text(json.dumps({"v": v}))

    def run(extra):
        return subprocess.run(
            [sys.executable, "-m", "guard_tpu.cli", "validate", "-r",
             str(rules), "-d", str(data), "--structured", "-o", "json",
             "--show-summary", "none"]
            + extra,
            capture_output=True,
            text=True,
            timeout=300,
        )

    cpu = run([])
    tpu = run(["--backend", "tpu"])
    assert cpu.returncode == tpu.returncode == 19
    assert json.loads(cpu.stdout) == json.loads(tpu.stdout)
