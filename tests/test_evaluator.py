"""Evaluator semantics, pinned to eval.rs / operators.rs behavior, plus
the full guard-examples expectation corpus as a golden suite."""

import pathlib

import pytest
import yaml

from guard_tpu.core.loader import load_document
from guard_tpu.core.parser import parse_rules_file
from guard_tpu.core.qresult import Status
from guard_tpu.core.scopes import RootScope
from guard_tpu.core.evaluator import eval_rule, eval_rules_file
from guard_tpu.core.values import from_plain


def run(rules: str, doc, rule_name=None) -> Status:
    rf = parse_rules_file(rules, "")
    root = from_plain(doc) if not isinstance(doc, str) else load_document(doc)
    scope = RootScope(rf, root)
    if rule_name is None:
        return eval_rules_file(rf, scope, None)
    return scope.rule_status(rule_name)


def test_missing_key_fails_clause():
    assert run("Resources.x.Type == 'T'\n", {"Resources": {}}) == Status.FAIL


def test_empty_on_missing_property_passes():
    # docs/CLAUSES.md: empty evaluates true for missing property keys
    assert run("Resources.S3.Properties.Tags empty\n", {"Resources": {"S3": {}}}) == Status.PASS


def test_exists_and_not_exists():
    doc = {"Resources": {"b": {"Type": "T"}}}
    assert run("Resources.b.Type exists\n", doc) == Status.PASS
    assert run("Resources.b.Missing !exists\n", doc) == Status.PASS
    assert run("Resources.b.Missing exists\n", doc) == Status.FAIL


def test_filter_empty_result_skips_block():
    rules = "Resources.*[ Type == 'AWS::EC2::Volume' ] {\n  Properties exists\n}\n"
    doc = {"Resources": {"b": {"Type": "Other"}}}
    assert run(rules, doc) == Status.SKIP


def test_filter_no_resources_fails():
    # QUERY_AND_FILTERING.md: {} or {Resources:{}} -> query FAILs the block
    rules = "Resources.*[ Type == 'AWS::EC2::Volume' ] {\n  Properties exists\n}\n"
    assert run(rules, {}) == Status.FAIL
    assert run(rules, {"Resources": {}}) == Status.FAIL


def test_some_vs_match_all():
    doc = {
        "Resources": {
            "r": {
                "Properties": {
                    "Tags": [
                        {"Key": "EndPROD", "Value": "NotAppStart"},
                        {"Key": "NotPRODEnd", "Value": "AppStart"},
                    ]
                }
            }
        }
    }
    independent = (
        "let resources = Resources.*\n"
        "rule r {\n"
        "  some %resources.Properties.Tags[*].Key == /PROD$/\n"
        "  some %resources.Properties.Tags[*].Value == /^App/\n"
        "}\n"
    )
    assert run(independent, doc, "r") == Status.PASS
    block_form = (
        "let resources = Resources.*\n"
        "rule r {\n"
        "  some %resources.Properties.Tags[*] {\n"
        "    Key == /PROD$/\n"
        "    Value == /^App/\n"
        "  }\n"
        "}\n"
    )
    assert run(block_form, doc, "r") == Status.FAIL


def test_in_operator_with_list():
    doc = {"Resources": {"v": {"Properties": {"VolumeType": "io1"}}}}
    assert (
        run("Resources.v.Properties.VolumeType IN ['io1','io2','gp3']\n", doc)
        == Status.PASS
    )
    assert (
        run("Resources.v.Properties.VolumeType IN ['gp2']\n", doc) == Status.FAIL
    )
    assert (
        run("Resources.v.Properties.VolumeType not IN ['gp2']\n", doc) == Status.PASS
    )


def test_range_in():
    doc = {"Resources": {"v": {"Properties": {"Size": 100}}}}
    assert run("Resources.v.Properties.Size IN r[50,200]\n", doc) == Status.PASS
    assert run("Resources.v.Properties.Size IN r(100,200]\n", doc) == Status.FAIL


def test_when_skip_gating():
    rules = (
        "rule gated when Resources.b.Missing exists {\n  Resources.b.Type == 'T'\n}\n"
    )
    assert run(rules, {"Resources": {"b": {"Type": "T"}}}, "gated") == Status.SKIP


def test_named_rule_dependency_and_negation():
    rules = (
        "rule a {\n  Resources exists\n}\n"
        "rule b when a {\n  Resources.x.T == 1\n}\n"
        "rule c {\n  not a\n}\n"
    )
    doc = {"Resources": {"x": {"T": 1}}}
    assert run(rules, doc, "b") == Status.PASS
    assert run(rules, doc, "c") == Status.FAIL


def test_parameterized_rule():
    rules = (
        "rule check_len(items) {\n  %items !empty\n}\n"
        "rule main {\n  check_len(Resources.*)\n}\n"
    )
    assert run(rules, {"Resources": {"a": {"x": 1}}}, "main") == Status.PASS
    assert run(rules, {"Resources": {}}, "main") == Status.FAIL


def test_type_block():
    rules = "AWS::S3::Bucket {\n  Properties.BucketName exists\n}\n"
    doc = {
        "Resources": {
            "b1": {"Type": "AWS::S3::Bucket", "Properties": {"BucketName": "x"}},
            "other": {"Type": "AWS::EC2::Instance"},
        }
    }
    assert run(rules, doc) == Status.PASS
    doc2 = {"Resources": {"other": {"Type": "AWS::EC2::Instance"}}}
    assert run(rules, doc2) == Status.SKIP


def test_scalar_equals_single_element_list():
    # UNIT_TESTING.md: Types: "PRIVATE" matches == against [*] projection
    rules = 'Resources.a.Types[*] == "PRIVATE"\n'
    assert run(rules, {"Resources": {"a": {"Types": "PRIVATE"}}}) == Status.PASS


def test_string_in_string_containment():
    doc = {"a": "10.0.0.0/24"}
    assert run("a IN '10.0.0.0/24,192.168.0.0/16'\n", doc) == Status.PASS


def test_variables_with_loops():
    rules = (
        "let ports = InputParameter.TcpBlockedPorts[*]\n"
        "rule ports_check {\n"
        "  %ports !empty\n"
        "  %ports {\n    this IN r[0,65535]\n  }\n"
        "}\n"
    )
    doc = {"InputParameter": {"TcpBlockedPorts": [21, 22, 110]}}
    assert run(rules, doc, "ports_check") == Status.PASS


def test_count_function():
    rules = (
        "let all = Resources.*\n"
        "let n = count(%all)\n"
        "rule r {\n  %n == 2\n}\n"
    )
    doc = {"Resources": {"a": {"x": 1}, "b": {"x": 2}}}
    assert run(rules, doc, "r") == Status.PASS


def test_join_and_to_upper():
    rules = (
        "let items = Resources.c.Collection[*]\n"
        "let joined = join(%items, ',')\n"
        "let upper = to_upper(%joined)\n"
        "rule r {\n  %upper == 'A,B,C'\n}\n"
    )
    doc = {"Resources": {"c": {"Collection": ["a", "b", "c"]}}}
    assert run(rules, doc, "r") == Status.PASS


def test_json_parse():
    rules = (
        "let raw = Resources.s.Policy\n"
        "let parsed = json_parse(%raw)\n"
        "rule r {\n  %parsed.Principal == '*'\n}\n"
    )
    doc = {"Resources": {"s": {"Policy": '{"Principal": "*"}'}}}
    assert run(rules, doc, "r") == Status.PASS


def test_keys_projection():
    rules = "Resources.x.Condition[ keys == /aws:[sS]ourceVpc/ ] !empty\n"
    doc = {"Resources": {"x": {"Condition": {"aws:sourceVpc": ["vpc-1"]}}}}
    assert run(rules, doc) == Status.PASS


def test_not_in_reverse_diff():
    doc = {"ports": [10, 20]}
    assert run("ports.* not IN [30, 40]\n", doc) == Status.PASS
    assert run("ports.* not IN [10, 40]\n", doc) == Status.FAIL


# ---------------------------------------------------------------------------
# golden corpus: every guard-examples test spec
# ---------------------------------------------------------------------------
def _example_cases():
    cases = []
    base = pathlib.Path("/root/reference/guard-examples")
    for guard in sorted(base.rglob("*.guard")):
        tests = guard.with_name(guard.stem + "-tests.yaml")
        if not tests.exists():
            continue
        specs = yaml.safe_load(tests.read_text()) or []
        for i, spec in enumerate(specs):
            rules = (spec.get("expectations", {}) or {}).get("rules", {}) or {}
            for rule_name, expected in rules.items():
                cases.append(
                    pytest.param(
                        guard,
                        spec.get("input"),
                        rule_name,
                        expected,
                        id=f"{guard.stem}-{i}-{rule_name}",
                    )
                )
    return cases


@pytest.mark.parametrize("guard,input_doc,rule_name,expected", _example_cases())
def test_reference_example_expectations(guard, input_doc, rule_name, expected):
    rf = parse_rules_file(guard.read_text(), guard.name)
    scope = RootScope(rf, from_plain(input_doc))
    assert scope.rule_status(rule_name).value == expected


def test_regex_replace_invalid_runtime_pattern_is_clean_error():
    """An invalid regex STRING argument (not parse-time validated like
    regex literals) must surface as a clean evaluation error, matching
    the reference's Regex::try_from error path (strings.rs:68) — found
    by the coverage-guided parser fuzzer as an uncaught re.error."""
    from guard_tpu.api import run_checks
    from guard_tpu.core.errors import GuardError

    rules = (
        'let arn = Resources.*.Arn\n'
        'rule r { %arn == regex_replace(%arn, "[", "x") }'
    )
    with pytest.raises(GuardError):
        run_checks('{"Resources": {"a": {"Arn": "arn:aws:x"}}}', rules)


def test_regex_replace_invalid_pattern_routes_doc_to_oracle():
    """Same invalid-pattern path through the TPU backend's function
    precompute: the raising doc lands in the error set (routed to the
    oracle, which reproduces the error), never a crash."""
    from guard_tpu.core.parser import parse_rules_file
    from guard_tpu.core.values import from_plain
    from guard_tpu.ops.fnvars import precompute_fn_values

    rules = (
        "let arn = Resources.*.Arn\n"
        'let fixed = regex_replace(%arn, "[", "x")\n'
        "rule r { %fixed exists }"
    )
    rf = parse_rules_file(rules, "t.guard")
    docs = [from_plain({"Resources": {"a": {"Arn": "arn:aws:x"}}})]
    _keys, _vals, errors = precompute_fn_values(rf, docs)
    assert errors == {0}
