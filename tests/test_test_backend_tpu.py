"""`test --backend tpu` (VERDICT r3 item 9): the expectation-suite
runner exercises the device path — statuses from the batched kernels,
rich output (verbose trees, error paths) from the oracle — with output
identical to the CPU backend."""

import pathlib
import random

import pytest

from guard_tpu.cli import run
from guard_tpu.utils.io import Reader, Writer

REPO = pathlib.Path(__file__).resolve().parent.parent
CORPUS = REPO / "corpus" / "rules"


def _run(args):
    w = Writer.buffered()
    rc = run(args, writer=w, reader=Reader())
    return rc, w.out.getvalue(), w.err.getvalue()


@pytest.mark.parametrize("fmt", ["single-line-summary", "json", "junit"])
def test_corpus_sample_identical_under_both_backends(fmt):
    rng = random.Random(fmt)
    sample = rng.sample(sorted(CORPUS.glob("*.guard")), 5)
    for g in sample:
        args_base = [
            "test",
            "--rules-file", str(g),
            "--test-data", str(CORPUS / "tests" / f"{g.stem}_tests.yaml"),
        ]
        if fmt != "single-line-summary":
            args_base += ["--output-format", fmt]
        cpu = _run(args_base + ["--backend", "cpu"])
        tpu = _run(args_base + ["--backend", "tpu"])
        assert cpu == tpu, f"{g.name} [{fmt}]: backend outputs differ"


def test_directory_mode_identical(tmp_path):
    # a small directory with the dir/tests/ pairing convention
    rules = tmp_path / "r1.guard"
    rules.write_text("rule named { Resources.*.Name exists }\n")
    tests_dir = tmp_path / "tests"
    tests_dir.mkdir()
    (tests_dir / "r1_tests.yaml").write_text(
        "- name: t1\n"
        "  input: {Resources: {a: {Name: x}}}\n"
        "  expectations: {rules: {named: PASS}}\n"
        "- name: t2\n"
        "  input: {Resources: {a: {}}}\n"
        "  expectations: {rules: {named: FAIL}}\n"
    )
    cpu = _run(["test", "-d", str(tmp_path), "--backend", "cpu"])
    tpu = _run(["test", "-d", str(tmp_path), "--backend", "tpu"])
    assert cpu == tpu
    assert cpu[0] == 0


def test_failing_expectation_exit_code_from_device(tmp_path):
    rules = tmp_path / "r1.guard"
    rules.write_text("rule named { Resources.*.Name exists }\n")
    spec = tmp_path / "t.yaml"
    spec.write_text(
        "- name: wrong\n"
        "  input: {Resources: {a: {Name: x}}}\n"
        "  expectations: {rules: {named: FAIL}}\n"
    )
    rc, out, _ = _run([
        "test", "--rules-file", str(rules), "--test-data", str(spec),
        "--backend", "tpu",
    ])
    assert rc == 7  # TEST_FAILURE_STATUS_CODE
    assert "Expected = FAIL" in out


def test_function_let_rules_identical(tmp_path):
    # review-found bug class: precomputable function lets must go
    # through the fn-precompute + re-encode contract, not a bare batch
    rules = tmp_path / "r.guard"
    rules.write_text(
        "let names = Resources.*.Name\n"
        "let up = to_upper(%names)\n"
        'rule upper_ok { %up == "X" }\n'
    )
    spec = tmp_path / "t.yaml"
    spec.write_text(
        "- name: t\n"
        "  input: {Resources: {a: {Name: x}}}\n"
        "  expectations: {rules: {upper_ok: PASS}}\n"
        "- name: t2\n"
        "  input: {Resources: {a: {Name: zz}}}\n"
        "  expectations: {rules: {upper_ok: FAIL}}\n"
    )
    base = ["test", "--rules-file", str(rules), "--test-data", str(spec)]
    cpu = _run(base + ["--backend", "cpu"])
    tpu = _run(base + ["--backend", "tpu"])
    assert cpu == tpu
    assert cpu[0] == 0


def test_verbose_stays_on_oracle(tmp_path):
    # verbose needs the record tree: the tpu flag must not change its
    # output either (the device path is bypassed)
    rules = tmp_path / "r1.guard"
    rules.write_text("rule named { Resources.*.Name exists }\n")
    spec = tmp_path / "t.yaml"
    spec.write_text(
        "- name: t\n"
        "  input: {Resources: {a: {Name: x}}}\n"
        "  expectations: {rules: {named: PASS}}\n"
    )
    base = ["test", "--rules-file", str(rules), "--test-data", str(spec), "-v"]
    assert _run(base + ["--backend", "cpu"]) == _run(base + ["--backend", "tpu"])
