"""Durability plane suite (guard_tpu/utils/journal.py,
guard_tpu/commands/gc.py): journal round-trips with torn-tail
truncation and stale-key cold starts, crash+resume byte parity with
zero device dispatches for journaled chunks, graceful SIGTERM/SIGINT
drain on sweep AND serve (injectable latches, no wall-clock asserts),
size-capped LRU store hygiene, and the ENOSPC degradation contract at
every persistence seam — a full disk turns checkpointing off, it
never changes a run's bytes or exit code."""

import json
import logging
import os
import signal

import pytest

from guard_tpu.commands.gc import Gc
from guard_tpu.commands.serve import Serve
from guard_tpu.commands.sweep import Sweep
from guard_tpu.ops.backend import dispatch_stats, reset_all_stats
from guard_tpu.utils import journal as jn
from guard_tpu.utils import telemetry
from guard_tpu.utils.faults import InjectedFault, reset_faults
from guard_tpu.utils.io import Reader, Writer

RULES = (
    "let b = Resources.*[ Type == 'AWS::S3::Bucket' ]\n"
    "rule sse when %b !empty { %b.Properties.Enc == true }\n"
)
# EMPTY on an int raises GuardError in the oracle: the doc's stderr
# line re-emits on every run, so replay must reproduce it from the
# journaled stderr, byte for byte
RULES_ERR = "rule em { Resources.R1.Properties.X !empty }\n"


def _resume_stats() -> dict:
    return telemetry.REGISTRY.group_stats("resume")


def _gc_stats() -> dict:
    return telemetry.REGISTRY.group_stats("gc")


@pytest.fixture(autouse=True)
def _fresh_durability(tmp_path, monkeypatch):
    """Private journal dir + clean counters/faults per test — journal
    keys are content-addressed, so shared fixture corpora would
    otherwise cross-replay between tests."""
    monkeypatch.setenv("GUARD_TPU_JOURNAL_DIR", str(tmp_path / "journal"))
    monkeypatch.delenv("GUARD_TPU_FAULT", raising=False)
    monkeypatch.delenv("GUARD_TPU_SWEEP_RESUME", raising=False)
    monkeypatch.delenv("GUARD_TPU_SWEEP_JOURNAL", raising=False)
    monkeypatch.delenv("GUARD_TPU_CACHE_MAX_BYTES", raising=False)
    reset_faults()
    reset_all_stats()
    yield
    reset_faults()
    reset_all_stats()


def _mk_corpus(tmp_path, n=12, fail=(3,), err=()):
    data = tmp_path / "data"
    data.mkdir(exist_ok=True)
    rp = tmp_path / "rules.guard"
    rp.write_text(RULES)
    for i in range(n):
        doc = {
            "Resources": {
                f"b{i}": {
                    "Type": "AWS::S3::Bucket",
                    "Properties": {"Enc": i not in fail},
                }
            }
        }
        if i in err:
            doc["Resources"]["R1"] = {"Properties": {"X": 7}}
        (data / f"d{i:02d}.json").write_text(json.dumps(doc))
    return [str(rp)], data


def _sweep(rules, data, manifest, **kw):
    kw.setdefault("chunk_size", 4)
    kw.setdefault("backend", "tpu")
    kw.setdefault("result_cache", False)
    w = Writer.buffered()
    cmd = Sweep(rules=rules, data=[str(data)], manifest=str(manifest), **kw)
    rc = cmd.execute(w, Reader.from_string(""))
    return rc, w.out.getvalue(), w.err.getvalue()


# ------------------------------------------------------ journal units


def test_run_key_sensitive_to_every_part(tmp_path):
    ra = tmp_path / "a.guard"
    rb = tmp_path / "b.guard"
    ra.write_text(RULES)
    rb.write_text(RULES + "\n# changed\n")
    d0 = tmp_path / "d0.json"
    d1 = tmp_path / "d1.json"
    d0.write_text("{}")
    d1.write_text('{"x": 1}')

    class _RF:
        def __init__(self, p):
            self.full_name = str(p)
            self.content = p.read_text()

    base = jn.run_key(
        jn.rules_digest([_RF(ra)]),
        jn.doc_manifest_digest([d0, d1]),
        "cfg0",
    )
    assert base == jn.run_key(
        jn.rules_digest([_RF(ra)]),
        jn.doc_manifest_digest([d0, d1]),
        "cfg0",
    )
    # rule content, doc content, doc ORDER and config each flip the key
    assert base != jn.run_key(
        jn.rules_digest([_RF(rb)]),
        jn.doc_manifest_digest([d0, d1]), "cfg0",
    )
    d1.write_text('{"x": 2}')
    assert base != jn.run_key(
        jn.rules_digest([_RF(ra)]),
        jn.doc_manifest_digest([d0, d1]), "cfg0",
    )
    d1.write_text('{"x": 1}')
    assert base != jn.run_key(
        jn.rules_digest([_RF(ra)]),
        jn.doc_manifest_digest([d1, d0]), "cfg0",
    )
    assert base != jn.run_key(
        jn.rules_digest([_RF(ra)]),
        jn.doc_manifest_digest([d0, d1]), "cfg1",
    )


def test_journal_round_trip():
    key = "k" * 64
    j = jn.SweepJournal(key, 3)
    recs = [
        {"chunk": i, "sig": f"s{i}", "counts": {"pass": i}}
        for i in range(3)
    ]
    j.append_chunk(0, recs[0], "", {})
    j.append_chunk(1, recs[1], "warned\n", {"injected_read": 1})
    j.append_chunk(2, recs[2], "", {})
    j.close()
    replay = jn.load_journal(key, n_chunks=3)
    assert sorted(replay) == [0, 1, 2]
    assert replay[1]["rec"] == recs[1]
    assert replay[1]["stderr"] == "warned\n"
    assert replay[1]["faults"] == {"injected_read": 1}
    assert _resume_stats()["chunks_journaled"] == 3


def test_journal_torn_tail_truncated():
    key = "t" * 64
    j = jn.SweepJournal(key, 4)
    j.append_chunk(0, {"chunk": 0}, "", {})
    j.append_chunk(1, {"chunk": 1}, "", {})
    j.close()
    path = jn.journal_path(key)
    # a torn append: half a record, no trailing newline
    with path.open("a") as f:
        f.write('{"kind": "chunk", "chunk": 2, "rec')
    before = _resume_stats()["torn_records_dropped"]
    replay = jn.load_journal(key, n_chunks=4)
    assert sorted(replay) == [0, 1]
    assert _resume_stats()["torn_records_dropped"] == before + 1


def test_journal_garbage_mid_file_truncates_rest():
    key = "g" * 64
    j = jn.SweepJournal(key, 4)
    j.append_chunk(0, {"chunk": 0}, "", {})
    j.close()
    path = jn.journal_path(key)
    with path.open("a") as f:
        f.write("NOT JSON AT ALL\n")
        f.write(json.dumps({
            "kind": "chunk", "chunk": 3, "rec": {"chunk": 3},
        }) + "\n")
    # everything after the torn line is untrusted by construction
    replay = jn.load_journal(key, n_chunks=4)
    assert sorted(replay) == [0]


def test_journal_header_mismatch_is_cold_start():
    key = "h" * 64
    j = jn.SweepJournal(key, 3)
    j.append_chunk(0, {"chunk": 0}, "", {})
    j.close()
    # a different chunk count means a different run shape: cold start
    assert jn.load_journal(key, n_chunks=5) == {}
    # absent journal is the stale-key case: {} without error
    assert jn.load_journal("n" * 64, n_chunks=3) == {}


def test_journal_last_record_wins():
    key = "w" * 64
    j = jn.SweepJournal(key, 2)
    j.append_chunk(0, {"v": 1}, "", {})
    j.append_chunk(0, {"v": 2}, "", {})
    j.close()
    replay = jn.load_journal(key, n_chunks=2)
    assert replay[0]["rec"] == {"v": 2}


# --------------------------------------------- crash + resume parity


def test_crash_resume_byte_identical(tmp_path, monkeypatch):
    rules, data = _mk_corpus(tmp_path, n=12, fail=(3,), err=())
    mpath = tmp_path / "m.jsonl"

    # leg A: uninterrupted baseline (its own journal dir — the same
    # run key must not leak into the crash leg's journal)
    monkeypatch.setenv("GUARD_TPU_JOURNAL_DIR", str(tmp_path / "jA"))
    reset_all_stats()
    base = _sweep(rules, data, mpath)
    d_base = dispatch_stats()
    base_manifest = mpath.read_text()
    assert base[0] == 19  # the seeded failing doc

    # leg B: killed at the second checkpoint, then resumed
    monkeypatch.setenv("GUARD_TPU_JOURNAL_DIR", str(tmp_path / "jB"))
    monkeypatch.setenv("GUARD_TPU_FAULT", "journal:nth=2")
    reset_faults()
    mpath.unlink()
    with pytest.raises(InjectedFault):
        _sweep(rules, data, mpath)
    monkeypatch.delenv("GUARD_TPU_FAULT")
    reset_faults()
    reset_all_stats()
    mpath.unlink()
    resumed = _sweep(rules, data, mpath, resume=True)
    d_res = dispatch_stats()
    s = _resume_stats()

    assert resumed == base
    assert mpath.read_text() == base_manifest
    assert s["runs_resumed"] == 1
    assert s["chunks_replayed"] == 1
    # the replayed chunk never touches the device
    assert 0 < d_res["dispatches"] < d_base["dispatches"]


def test_full_replay_zero_dispatches(tmp_path):
    rules, data = _mk_corpus(tmp_path, n=8)
    mpath = tmp_path / "m.jsonl"
    base = _sweep(rules, data, mpath)
    base_manifest = mpath.read_text()
    mpath.unlink()
    reset_all_stats()
    replay = _sweep(rules, data, mpath, resume=True)
    assert replay == base
    assert mpath.read_text() == base_manifest
    assert dispatch_stats()["dispatches"] == 0
    assert _resume_stats()["chunks_replayed"] == 2


def test_resume_replays_journaled_stderr(tmp_path, monkeypatch):
    """Oracle-error docs write stderr every run; a replayed chunk must
    re-emit the journaled bytes, not silence them."""
    rp = tmp_path / "rules.guard"
    rp.write_text(RULES_ERR)
    data = tmp_path / "data"
    data.mkdir()
    for i in range(4):
        (data / f"d{i}.json").write_text(
            json.dumps({"Resources": {"R1": {"Properties": {"X": 7}}}})
        )
    mpath = tmp_path / "m.jsonl"
    base = _sweep([str(rp)], data, mpath, chunk_size=2)
    assert base[2]  # the oracle errors hit stderr
    mpath.unlink()
    reset_all_stats()
    replay = _sweep([str(rp)], data, mpath, chunk_size=2, resume=True)
    assert replay == base
    assert dispatch_stats()["dispatches"] == 0


def test_stale_journal_is_logged_cold_start(tmp_path):
    rules, data = _mk_corpus(tmp_path, n=8)
    mpath = tmp_path / "m.jsonl"
    _sweep(rules, data, mpath)
    # touching one doc changes the run key: resume finds no journal
    p0 = sorted(data.glob("d*.json"))[0]
    doc = json.loads(p0.read_text())
    doc["__touch"] = 1
    p0.write_text(json.dumps(doc))
    mpath.unlink()
    reset_all_stats()
    _sweep(rules, data, mpath, resume=True)
    s = _resume_stats()
    assert s["stale_cold_starts"] == 1
    assert s["chunks_replayed"] == 0
    assert dispatch_stats()["dispatches"] > 0


def test_no_journal_flag_writes_nothing(tmp_path):
    rules, data = _mk_corpus(tmp_path, n=4)
    _sweep(rules, data, tmp_path / "m.jsonl", journal=False)
    assert not list(jn.journal_dir().glob("*.journal.jsonl"))
    assert _resume_stats()["chunks_journaled"] == 0


def test_journal_env_escape_hatch(tmp_path, monkeypatch):
    monkeypatch.setenv("GUARD_TPU_SWEEP_JOURNAL", "0")
    rules, data = _mk_corpus(tmp_path, n=4)
    _sweep(rules, data, tmp_path / "m.jsonl")
    assert not list(jn.journal_dir().glob("*.journal.jsonl"))


def test_resume_auto_env(tmp_path, monkeypatch):
    rules, data = _mk_corpus(tmp_path, n=8)
    mpath = tmp_path / "m.jsonl"
    base = _sweep(rules, data, mpath)
    mpath.unlink()
    monkeypatch.setenv("GUARD_TPU_SWEEP_RESUME", "auto")
    reset_all_stats()
    replay = _sweep(rules, data, mpath)  # no --resume flag needed
    assert replay == base
    assert dispatch_stats()["dispatches"] == 0


# ------------------------------------------------------ graceful drain


class _TripAfter(jn.DrainLatch):
    """Injectable latch: trips itself after N `tripped()` polls — the
    deterministic stand-in for a SIGTERM landing mid-run (no sleeps,
    no wall-clock)."""

    def __init__(self, polls: int):
        super().__init__()
        self._polls = polls

    def tripped(self) -> bool:
        if not super().tripped():
            self._polls -= 1
            if self._polls <= 0:
                self.trip("test")
        return super().tripped()


def test_sweep_drain_finishes_chunk_then_exits_75(tmp_path):
    rules, data = _mk_corpus(tmp_path, n=12)
    mpath = tmp_path / "m.jsonl"
    # trip on the second poll: chunk 0 completes, the loop-top check
    # fires before chunk 1
    rc, out, _err = _sweep(
        rules, data, mpath, drain_latch=_TripAfter(2)
    )
    assert rc == jn.DRAIN_EXIT_CODE == 75
    summary = json.loads(out.strip().splitlines()[-1])
    assert 0 < summary["evaluated"] < summary["chunks"]
    assert _resume_stats()["drained_sessions"] == 1
    # every completed chunk is journaled: resume finishes the rest
    # and reproduces an uninterrupted run's manifest exactly
    base_m = tmp_path / "base.jsonl"
    rc_base, _, _ = _sweep(rules, data, base_m)
    mpath.unlink()
    reset_all_stats()
    rc2, _out2, _err2 = _sweep(rules, data, mpath, resume=True)
    assert rc2 == rc_base == 19  # the seeded failing doc, not a drain
    assert _resume_stats()["chunks_replayed"] >= 1
    assert mpath.read_text() == base_m.read_text()


def test_sweep_sigterm_handler_trips_latch(tmp_path):
    """A real SIGTERM delivered mid-run drains instead of dying: the
    handler installed by execute trips the latch, the in-flight chunk
    finishes, and the run exits 75 with a synced journal."""
    rules, data = _mk_corpus(tmp_path, n=12)

    class _SignalOnPoll(jn.DrainLatch):
        def __init__(self):
            super().__init__()
            self._sent = False

        def tripped(self) -> bool:
            if not self._sent:
                self._sent = True
                os.kill(os.getpid(), signal.SIGTERM)
            return super().tripped()

    rc, out, _err = _sweep(
        rules, data, tmp_path / "m.jsonl",
        drain_latch=_SignalOnPoll(),
    )
    assert rc == jn.DRAIN_EXIT_CODE
    summary = json.loads(out.strip().splitlines()[-1])
    assert summary["evaluated"] < summary["chunks"]
    # the pre-existing handler is restored after execute
    assert signal.getsignal(signal.SIGTERM) == signal.SIG_DFL


def test_serve_draining_envelope_and_exit_code():
    latch = jn.DrainLatch()
    latch.trip("test")
    srv = Serve(stdio=True, drain_latch=latch)
    env = srv.handle_line(json.dumps({
        "rules": ["rule ok { a exists }"], "data": ['{"a": 1}'],
    }))
    assert env["code"] == 5
    assert env["error_class"] == "Draining"
    assert isinstance(env["retry_after_ms"], int)
    # a stdio session with a tripped latch answers the pending line
    # with the Draining envelope, then exits the drain code
    w = Writer.buffered()
    rc = srv.execute(w, Reader.from_string(
        json.dumps({"rules": ["rule ok { a exists }"],
                    "data": ['{"a": 1}']}) + "\n"
    ))
    assert rc == jn.DRAIN_EXIT_CODE
    resps = [json.loads(l) for l in w.out.getvalue().splitlines()]
    assert resps and all(
        r["error_class"] == "Draining" for r in resps
    )
    assert _resume_stats()["drained_sessions"] >= 1


def test_serve_drains_after_answering_in_flight():
    """The latch trips between requests: answered lines keep their
    real envelopes, the next read answers Draining, exit is 75."""
    latch = jn.DrainLatch()
    srv = Serve(stdio=True, drain_latch=latch)
    first = srv.handle_line(json.dumps({
        "rules": ["rule ok { a exists }"], "data": ['{"a": 1}'],
    }))
    assert first["code"] == 0
    latch.trip("test")
    second = srv.handle_line(json.dumps({
        "rules": ["rule ok { a exists }"], "data": ['{"a": 1}'],
    }))
    assert second["error_class"] == "Draining"


def test_install_signal_drain_restores_handlers():
    latch = jn.DrainLatch()
    prev_term = signal.getsignal(signal.SIGTERM)
    restore = jn.install_signal_drain(latch)
    try:
        os.kill(os.getpid(), signal.SIGTERM)
        assert latch.tripped()
        assert latch.reason == "SIGTERM"
    finally:
        restore()
    assert signal.getsignal(signal.SIGTERM) == prev_term


# -------------------------------------------------------- gc hygiene


def _gc_run(**kw):
    w = Writer.buffered()
    rc = Gc(**kw).execute(w, Reader.from_string(""))
    return rc, json.loads(w.out.getvalue().strip())


def _seed_store(d, n=4, size=100, suffix=".journal.jsonl"):
    d.mkdir(parents=True, exist_ok=True)
    paths = []
    for i in range(n):
        p = d / f"e{i}{suffix}"
        p.write_bytes(b"x" * size)
        # deterministic LRU order: e0 oldest ... e{n-1} newest
        os.utime(p, (1000.0 + i, 1000.0 + i))
        paths.append(p)
    return paths


def test_gc_evicts_oldest_first_to_cap(tmp_path, monkeypatch):
    jd = tmp_path / "journal"
    paths = _seed_store(jd, n=4, size=100)
    monkeypatch.setenv("GUARD_TPU_JOURNAL_DIR", str(jd))
    monkeypatch.setenv("GUARD_TPU_PLAN_CACHE_DIR", str(tmp_path / "p"))
    monkeypatch.setenv(
        "GUARD_TPU_RESULT_CACHE_DIR", str(tmp_path / "r")
    )
    rc, doc = _gc_run(max_bytes=250)
    assert rc == 0
    st = doc["gc"]["journal"]
    assert st["bytes_before"] == 400
    assert st["evicted"] == 2
    assert st["bytes_after"] == 200
    # LRU: the two OLDEST entries went, the newest two survive
    assert not paths[0].exists() and not paths[1].exists()
    assert paths[2].exists() and paths[3].exists()
    assert _gc_stats()["files_evicted"] == 2
    assert _gc_stats()["bytes_evicted"] == 200


def test_gc_dry_run_reports_without_deleting(tmp_path, monkeypatch):
    jd = tmp_path / "journal"
    paths = _seed_store(jd, n=3, size=100)
    monkeypatch.setenv("GUARD_TPU_JOURNAL_DIR", str(jd))
    monkeypatch.setenv("GUARD_TPU_PLAN_CACHE_DIR", str(tmp_path / "p"))
    monkeypatch.setenv(
        "GUARD_TPU_RESULT_CACHE_DIR", str(tmp_path / "r")
    )
    rc, doc = _gc_run(max_bytes=100, dry_run=True)
    assert rc == 0
    assert doc["dry_run"] is True
    assert doc["gc"]["journal"]["evicted"] == 2
    assert all(p.exists() for p in paths)
    assert _gc_stats()["files_evicted"] == 0


def test_gc_undeletable_entry_skipped_exit_0(tmp_path, monkeypatch):
    jd = tmp_path / "journal"
    paths = _seed_store(jd, n=3, size=100)
    monkeypatch.setenv("GUARD_TPU_JOURNAL_DIR", str(jd))
    monkeypatch.setenv("GUARD_TPU_PLAN_CACHE_DIR", str(tmp_path / "p"))
    monkeypatch.setenv(
        "GUARD_TPU_RESULT_CACHE_DIR", str(tmp_path / "r")
    )
    from pathlib import Path

    real_unlink = Path.unlink
    victim = str(paths[0])

    def flaky_unlink(self, *a, **kw):
        if str(self) == victim:
            raise PermissionError("synthetic EPERM")
        return real_unlink(self, *a, **kw)

    monkeypatch.setattr(Path, "unlink", flaky_unlink)
    rc, doc = _gc_run(max_bytes=100)
    assert rc == 0  # hygiene is advisory: never a failed command
    assert _gc_stats()["evict_errors"] == 1
    # the undeletable oldest was skipped; the next-oldest made room
    assert paths[0].exists() and not paths[1].exists()


def test_gc_vanished_entry_counts_bytes(tmp_path, monkeypatch):
    """Crash-mid-evict / concurrent gc: a file already gone when the
    unlink lands is not an error — the bytes are gone either way."""
    jd = tmp_path / "journal"
    paths = _seed_store(jd, n=3, size=100)
    monkeypatch.setenv("GUARD_TPU_JOURNAL_DIR", str(jd))
    monkeypatch.setenv("GUARD_TPU_PLAN_CACHE_DIR", str(tmp_path / "p"))
    monkeypatch.setenv(
        "GUARD_TPU_RESULT_CACHE_DIR", str(tmp_path / "r")
    )
    from pathlib import Path

    real_unlink = Path.unlink
    victim = str(paths[0])

    def racing_unlink(self, *a, **kw):
        if str(self) == victim:
            real_unlink(self)  # the "concurrent gc" got there first
        return real_unlink(self, *a, **kw)

    monkeypatch.setattr(Path, "unlink", racing_unlink)
    rc, doc = _gc_run(max_bytes=200)
    assert rc == 0
    assert doc["gc"]["journal"]["evicted"] == 1
    assert _gc_stats()["evict_errors"] == 0


def test_gc_reaps_only_aged_orphan_tmps(tmp_path, monkeypatch):
    jd = tmp_path / "journal"
    jd.mkdir(parents=True)
    old = jd / "e.result.json.tmp.1234"
    old.write_bytes(b"orphan")
    os.utime(old, (1000.0, 1000.0))
    fresh = jd / "f.result.json.tmp.5678"
    fresh.write_bytes(b"live writer mid-rename")
    monkeypatch.setenv("GUARD_TPU_JOURNAL_DIR", str(jd))
    monkeypatch.setenv("GUARD_TPU_PLAN_CACHE_DIR", str(tmp_path / "p"))
    monkeypatch.setenv(
        "GUARD_TPU_RESULT_CACHE_DIR", str(tmp_path / "r")
    )
    rc, doc = _gc_run()
    assert rc == 0
    assert doc["gc"]["journal"]["tmps_reaped"] == 1
    assert not old.exists()
    assert fresh.exists()
    assert _gc_stats()["orphan_tmps_reaped"] == 1


def test_gc_env_cap(tmp_path, monkeypatch):
    jd = tmp_path / "journal"
    _seed_store(jd, n=4, size=100)
    monkeypatch.setenv("GUARD_TPU_JOURNAL_DIR", str(jd))
    monkeypatch.setenv("GUARD_TPU_PLAN_CACHE_DIR", str(tmp_path / "p"))
    monkeypatch.setenv(
        "GUARD_TPU_RESULT_CACHE_DIR", str(tmp_path / "r")
    )
    monkeypatch.setenv("GUARD_TPU_CACHE_MAX_BYTES", "300")
    rc, doc = _gc_run()
    assert rc == 0
    assert doc["max_bytes"] == 300
    assert doc["gc"]["journal"]["evicted"] == 1


# -------------------------------------- ENOSPC degradation contract


@pytest.mark.parametrize("workers", [0, 2])
@pytest.mark.parametrize("pack", [True, False])
def test_journal_enospc_degrades_to_journal_off_parity(
    tmp_path, monkeypatch, workers, pack
):
    """A full disk at the journal seam turns checkpointing off with
    ONE warning — the run's stdout/stderr/manifest/exit code stay
    byte-identical to an explicit --no-journal run, across worker
    counts and pack modes."""
    rules, data = _mk_corpus(tmp_path, n=12, fail=(3,))
    mpath = tmp_path / "m.jsonl"
    off = _sweep(
        rules, data, mpath, journal=False,
        ingest_workers=workers, pack_rules=pack,
    )
    off_manifest = mpath.read_text()

    def broken_write(self, rec):
        raise OSError(28, "No space left on device")

    monkeypatch.setattr(jn.SweepJournal, "_write_line", broken_write)
    warned = []

    class _Catch(logging.Handler):
        def emit(self, record):
            warned.append(record.getMessage())

    h = _Catch(level=logging.WARNING)
    logging.getLogger("guard_tpu.journal").addHandler(h)
    mpath.unlink()
    reset_all_stats()
    try:
        on = _sweep(
            rules, data, mpath,
            ingest_workers=workers, pack_rules=pack,
        )
    finally:
        logging.getLogger("guard_tpu.journal").removeHandler(h)
    assert on == off
    assert mpath.read_text() == off_manifest
    assert _resume_stats()["journal_degraded"] == 1
    assert len(warned) == 1  # one warning, not one per chunk


def test_store_write_fault_degrades_result_store(tmp_path, monkeypatch):
    from guard_tpu.cache import results as rcache

    monkeypatch.setenv(
        "GUARD_TPU_RESULT_CACHE_DIR", str(tmp_path / "results")
    )
    monkeypatch.setenv("GUARD_TPU_FAULT", "store_write:rate=1.0:seed=1")
    reset_faults()
    assert rcache.store_entry("k" * 64, {"name": "d"}) is False
    assert not list((tmp_path / "results").glob("*.result.json"))


def test_store_write_fault_degrades_ledger(tmp_path, monkeypatch):
    from guard_tpu.utils import ledger

    monkeypatch.setenv("GUARD_TPU_LEDGER_DIR", str(tmp_path / "ledger"))
    monkeypatch.setenv("GUARD_TPU_FAULT", "store_write:rate=1.0:seed=1")
    reset_faults()
    warned = []

    class _Catch(logging.Handler):
        def emit(self, record):
            warned.append(record.getMessage())

    h = _Catch(level=logging.WARNING)
    logging.getLogger("guard_tpu.ledger").addHandler(h)
    try:
        rec = ledger.append_record("sweep", exit_code=0)
    finally:
        logging.getLogger("guard_tpu.ledger").removeHandler(h)
    assert rec is None
    assert warned
    assert not (tmp_path / "ledger" / "ledger.jsonl").exists()


def test_store_write_fault_degrades_plan_store(tmp_path, monkeypatch):
    from guard_tpu.commands.validate import RuleFile
    from guard_tpu.core.parser import parse_rules_file
    from guard_tpu.ops import plan as plan_mod

    monkeypatch.setenv("GUARD_TPU_PLAN_CACHE_DIR", str(tmp_path / "plans"))
    rf = RuleFile(
        name="r.guard", full_name="r.guard", content=RULES,
        rules=parse_rules_file(RULES, "r.guard"),
    )
    plan = plan_mod.build_plan([rf])
    digest = plan_mod.plan_digest([rf])
    monkeypatch.setenv("GUARD_TPU_FAULT", "store_write:rate=1.0:seed=1")
    reset_faults()
    assert plan_mod.save_plan(plan, digest) is False
    assert not list((tmp_path / "plans").glob("*.plan"))


# --------------------------------------------- ledger resume records


def test_resumed_session_pops_resume_info(tmp_path):
    rules, data = _mk_corpus(tmp_path, n=8)
    mpath = tmp_path / "m.jsonl"
    _sweep(rules, data, mpath)
    mpath.unlink()
    _sweep(rules, data, mpath, resume=True)
    info = jn.pop_resume_info()
    assert info is not None
    assert info["chunks_replayed"] == 2
    assert isinstance(info["resumed_from"], str)
    # read-then-clear: the epilogue consumes it exactly once
    assert jn.pop_resume_info() is None


def test_report_surfaces_resume_rate(tmp_path, monkeypatch):
    from guard_tpu.commands.ops_report import OpsReport
    from guard_tpu.utils import ledger

    monkeypatch.setenv("GUARD_TPU_LEDGER_DIR", str(tmp_path))
    ledger.append_record("sweep", exit_code=0)
    ledger.append_record(
        "sweep", exit_code=0,
        extra={"resumed_from": "k" * 64, "chunks_replayed": 5},
    )
    w = Writer.buffered()
    rc = OpsReport().execute(w, Reader.from_string(""))
    assert rc == 0
    out = w.out.getvalue()
    assert "resume rate: 50.0%" in out
    assert "5 chunks replayed" in out
