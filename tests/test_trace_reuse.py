"""Literals-as-inputs executable reuse (VERDICT r4 item 2): the kernel
trace depends only on rule STRUCTURE — interned literal ids ride in a
runtime (L,) array (ir.CompiledRules.lit_values) — so re-compiling the
same rule file against a NEW corpus (the next validate invocation in a
serve session, the next sweep chunk) reuses the jitted evaluator and
its per-bucket executables instead of re-tracing and re-compiling."""

import json

import numpy as np

from guard_tpu.core.parser import parse_rules_file
from guard_tpu.core.scopes import RootScope
from guard_tpu.core.evaluator import eval_rules_file
from guard_tpu.core.values import from_plain
from guard_tpu.ops.encoder import encode_batch
from guard_tpu.ops.ir import compile_rules_file, trace_signature
from guard_tpu.parallel import mesh as mesh_mod

RULES = """\
rule tagged {
    Resources.*[ Type == "AWS::S3::Bucket" ] {
        Properties.Tags !empty
        Properties.Name == /prod-/
    }
}
rule sized when tagged {
    Resources.* { Properties.Size >= 10 }
}
"""


def _docs(seed: int, n: int = 6):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        # per-seed unique strings: corpus B interns a disjoint string
        # set, so every literal id differs from corpus A's
        out.append(
            {
                "Resources": {
                    f"r{seed}_{i}_{int(rng.integers(1e6))}": {
                        "Type": "AWS::S3::Bucket",
                        "Properties": {
                            "Tags": [f"t{seed}_{i}"],
                            "Name": f"prod-{seed}-{i}" if i % 2 else f"dev-{i}",
                            "Size": int(rng.integers(1, 30)),
                        },
                    }
                }
            }
        )
    return [from_plain(d) for d in out]


def _oracle(rf, docs):
    from guard_tpu.core.qresult import Status

    to_int = {Status.PASS: 0, Status.FAIL: 1, Status.SKIP: 2}
    out = []
    for doc in docs:
        scope = RootScope(rf, doc)
        eval_rules_file(rf, scope, None)
        root = scope.reset_recorder().extract()
        out.append(
            [to_int[c.container.payload.status] for c in root.children]
        )
    return out


def test_signature_is_corpus_independent():
    rf = parse_rules_file(RULES, "r.guard")
    _, i1 = encode_batch(_docs(1))
    # corpus B interns a scrambling prefix doc first, so every shared
    # string lands on a DIFFERENT id than in corpus A
    scramble = from_plain({"zq": {"ww": 1}, "ab": "cd"})
    _, i2 = encode_batch([scramble] + _docs(2))
    c1 = compile_rules_file(rf, i1)
    c2 = compile_rules_file(rf, i2)
    assert trace_signature(c1) == trace_signature(c2)
    # distinct ids, same structure
    assert c1.lit_names == c2.lit_names
    assert not np.array_equal(c1.lit_values(), c2.lit_values())


def test_executable_reuse_across_corpora():
    rf = parse_rules_file(RULES, "r.guard")

    def statuses(seed):
        docs = _docs(seed)
        batch, interner = encode_batch(docs)
        compiled = compile_rules_file(rf, interner)
        assert not compiled.host_rules
        ev = mesh_mod.ShardedBatchEvaluator(compiled)
        st, _, host = ev.evaluate_bucketed(batch)
        assert not host
        return ev, st, docs

    ev1, st1, docs1 = statuses(1)
    n_cached = len(mesh_mod._SHARED_FNS)
    traces_before = ev1._fn._cache_size()

    ev2, st2, docs2 = statuses(2)
    # same jitted function object — no new cache entry, and the second
    # corpus' evaluation at the same bucket shape did NOT retrace
    assert ev2._fn is ev1._fn
    assert len(mesh_mod._SHARED_FNS) == n_cached
    assert ev2._fn._cache_size() == traces_before

    # bit-exact against the oracle on both corpora (the runtime lits
    # binding, not the trace, carries the corpus-specific ids)
    for st, docs in ((st1, docs1), (st2, docs2)):
        expect = _oracle(rf, docs)
        got = [[int(v) for v in row] for row in st]
        assert got == expect


def test_validate_invocations_share_executables(tmp_path):
    """End-to-end: two `validate --backend tpu` invocations (the serve
    request / sweep chunk shape) against different corpora share the
    jitted evaluator."""
    from guard_tpu.cli import run
    from guard_tpu.utils.io import Reader, Writer

    (tmp_path / "r.guard").write_text(RULES)
    for seed in (7, 8):
        data = tmp_path / f"data{seed}"
        data.mkdir()
        for i in range(3):
            (data / f"t{i}.json").write_text(
                json.dumps(
                    {
                        "Resources": {
                            f"u{seed}_{i}": {
                                "Type": "AWS::S3::Bucket",
                                "Properties": {
                                    "Tags": [f"x{seed}{i}"],
                                    "Name": f"prod-{seed}-{i}",
                                    "Size": 20,
                                },
                            }
                        }
                    }
                )
            )

    def go(seed):
        w = Writer.buffered()
        rc = run(
            ["validate", "-r", str(tmp_path / "r.guard"),
             "-d", str(tmp_path / f"data{seed}"), "--backend", "tpu"],
            writer=w, reader=Reader(),
        )
        return rc, w.out.getvalue()

    rc1, _ = go(7)
    n_cached = len(mesh_mod._SHARED_FNS)
    key1 = next(reversed(mesh_mod._SHARED_FNS))
    traces = mesh_mod._SHARED_FNS[key1][0]._cache_size()
    rc2, _ = go(8)
    assert rc1 == rc2 == 0
    assert len(mesh_mod._SHARED_FNS) == n_cached
    assert mesh_mod._SHARED_FNS[key1][0]._cache_size() == traces
