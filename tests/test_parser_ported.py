"""Grammar acceptance/rejection batch ported from the reference's
parser tests (/root/reference/guard/src/rules/parser_tests.rs) at the
rules-file level: the clause/value/range/list combinator cases wrapped
in minimal rules, asserting parse success or failure exactly as the
reference's combinators do (test_parse_float:138, test_broken_lists
:291, test_range_type_failures:516, test_clause_failures:1891,
test_keys_keyword:1320, test_parse_value_with_comments:533)."""

import pytest

from guard_tpu.core.errors import GuardError
from guard_tpu.core.parser import parse_rules_file


ACCEPT = [
    # floats (test_parse_float) — fraction or signed exponent gate,
    # maximal consume after
    "rule r { x == 10.0 }",
    "rule r { x == 10.2 }",
    "rule r { x == 1.5e3 }",
    "rule r { x == 2e+10 }",
    "rule r { x == 1.25E-2 }",
    # lists incl. nesting and empties (test_lists_success)
    "rule r { x == [] }",
    "rule r { x in [1, 2, 3] }",
    "rule r { x in [[1, 2], [3]] }",
    "rule r { x in ['a', \"b\"] }",
    "rule r { x in [1,\n # comment\n 2] }",
    # maps (test_map_success): keys bare/quoted, nesting
    'rule r { x == { key: 1, value: "there" } }',
    "rule r { x == { 'quoted': [1, 2], inner: { a: true } } }",
    "rule r { x == {} }",
    # ranges (test_range_type_success)
    "rule r { x in r(10, 20) }",
    "rule r { x in r[10, 20] }",
    "rule r { x in r(10, 20] }",
    "rule r { x in r[10.2, 50.5) }",
    # comments everywhere (test_parse_value_with_comments,
    # test_white_space_with_comments)
    "# lead\nrule r { # inner\n x == 1234 # trail\n }\n# end",
    # keys keyword (test_keys_keyword)
    "rule r { x[ keys == /aws/ ] !empty }",
    "rule r { x[ keys in ['a', 'b'] ] !empty }",
    "rule r { x[ keys != 'c' ] !empty }",
    # custom messages (clause suffix)
    "rule r { x == 10 << must be ten >> }",
    "rule r { x exists\n<<\nmult不line\n>> }",
    # dotted access variants (test_dotted_access)
    "rule r { a.b.c.d exists }",
    "rule r { a.'b c'.\"d.e\" exists }",
    "rule r { a.*.b[*].c[0] exists }",
    "rule r { %var.a.b exists\n}\nrule s { x exists }",
]

REJECT = [
    # broken lists (test_broken_lists)
    "rule r { x in [ }",
    # paren range without the r prefix (test_range_type_failures)
    "rule r { x in (10, 20) }",
    # missing access / missing RHS (test_clause_failures)
    "rule r { > 10 }",
    "rule r { x == << message >> }",
    "rule r { x > << message >> }",
    "rule r { x != << message >> }",
    # empty rule block
    "rule r { }",
    # unterminated string / regex
    "rule r { x == 'abc }",
    "rule r { x == /abc }",
    # bare exponent is not a float and leaves residue
    "rule r { x == 2e3 }",
]


@pytest.mark.parametrize("text", ACCEPT)
def test_grammar_accepts(text):
    parse_rules_file(text, "a.guard")


@pytest.mark.parametrize("text", REJECT)
def test_grammar_rejects(text):
    with pytest.raises(GuardError):
        parse_rules_file(text, "r.guard")
