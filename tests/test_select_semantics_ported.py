"""Ported legacy-engine `select`/`resolve_query` semantics cases
(VERDICT r2 item 8): the reference's Guard-2.0 evaluator survives only
as `resolve_query` behind `PathAwareValue::select`
(/root/reference/guard/src/rules/path_value.rs:599-891), exercised by
`evaluate_tests.rs` (test_iam_subselections:937,
test_rules_with_some_clauses:1101, test_support_for_atleast_one_match
_clause:1178). This repo deliberately skips the legacy engine (README
scope note); these ported cases prove the claim that the MODERN query
walk (core/scopes.py) covers the `select` semantics those tests pin —
same selections (paths and values), same statuses."""

import pytest

from guard_tpu.core.parser import parse_rules_file
from guard_tpu.core.qresult import RESOLVED, Status
from guard_tpu.core.scopes import RootScope
from guard_tpu.core.evaluator import eval_rules_file
from guard_tpu.core.values import from_plain


def _select(query: str, doc_plain) -> list:
    """Resolve a standalone query against a document through the
    modern walk (the analogue of PathAwareValue::select with a dummy
    variable resolver)."""
    rf = parse_rules_file(f"let q = {query}\nrule r {{ %q !empty }}", "s.guard")
    aq = rf.assignments[0].value
    scope = RootScope(rf, from_plain(doc_plain))
    return [
        r.value for r in scope.query(aq.query) if r.tag == RESOLVED
    ]


def _rule_status(rules: str, doc_plain, name: str) -> str:
    from guard_tpu.commands.report import rule_statuses_from_root

    rf = parse_rules_file(rules, "s.guard")
    scope = RootScope(rf, from_plain(doc_plain))
    eval_rules_file(rf, scope, None)
    root = scope.reset_recorder().extract()
    return rule_statuses_from_root(root)[name].value


# evaluate_tests.rs:937-1098 (test_iam_subselections)
IAM_DOC = {
    "Resources": {
        "one": {
            "Type": "AWS::IAM::Role",
            "Properties": {
                "Tags": [{"Key": "TestRole", "Value": ""}],
                "PermissionsBoundary": "aws:arn",
            },
        },
        "two": {
            "Type": "AWS::IAM::Role",
            "Properties": {"Tags": [{"Key": "TestRole", "Value": ""}]},
        },
        "three": {
            "Type": "AWS::IAM::Role",
            "Properties": {"Tags": [], "PermissionsBoundary": "aws:arn"},
        },
        "four": {
            "Type": "AWS::IAM::Role",
            "Properties": {"Tags": [{"Key": "Prod", "Value": ""}]},
        },
    }
}


def test_iam_subselections_single():
    selected = _select(
        'Resources.*[ Type == "AWS::IAM::Role" '
        'Properties.Tags[ Key == "TestRole" ] !empty '
        "Properties.PermissionsBoundary !exists ]",
        IAM_DOC,
    )
    assert [v.path.s for v in selected] == ["/Resources/two"]


def test_iam_subselections_disjunction():
    selected = _select(
        'Resources.*[ Type == "AWS::IAM::Role" '
        'Properties.Tags[ Key == "TestRole" or Key == "Prod" ] !empty '
        "Properties.PermissionsBoundary !exists ]",
        IAM_DOC,
    )
    assert [v.path.s for v in selected] == [
        "/Resources/two",
        "/Resources/four",
    ]


IAM_RULES = """
let iam_roles = Resources.*[ Type == "AWS::IAM::Role"  ]

rule deny_permissions_boundary_iam_role when %iam_roles !empty {
    %iam_roles[
        Properties.Tags[ Key == "TestRole" ] !empty
        Properties.PermissionsBoundary !exists
    ] !empty
}
"""


def test_iam_subselection_rule_pass_fail():
    assert (
        _rule_status(IAM_RULES, IAM_DOC, "deny_permissions_boundary_iam_role")
        == "PASS"
    )
    fail_doc = {
        "Resources": {
            "one": {
                "Type": "AWS::IAM::Role",
                "Properties": {"Tags": [{"Key": "Prod", "Value": ""}]},
            }
        }
    }
    assert (
        _rule_status(IAM_RULES, fail_doc, "deny_permissions_boundary_iam_role")
        == "FAIL"
    )


# evaluate_tests.rs:1101-1176 (test_rules_with_some_clauses)
def test_some_clause_selection():
    doc = {
        "Resources": {
            "CounterTaskDefExecutionRole5959CB2D": {
                "Type": "AWS::IAM::Role",
                "Properties": {
                    "PermissionsBoundary": {"Fn::Sub": "arn::boundary"},
                    "Tags": [{"Key": "TestRole", "Value": ""}],
                },
            },
            "BlankRole001": {
                "Type": "AWS::IAM::Role",
                "Properties": {"Tags": [{"Key": "FooBar", "Value": ""}]},
            },
            "BlankRole002": {
                "Type": "AWS::IAM::Role",
                "Properties": {},
            },
        }
    }
    selected = _select(
        "some Resources.*[ Type == 'AWS::IAM::Role' ]"
        ".Properties.Tags[ Key == /[A-Za-z0-9]+Role/ ]",
        doc,
    )
    assert len(selected) == 1
    assert selected[0].val.values["Key"].val == "TestRole"


# evaluate_tests.rs:1178-1253 (test_support_for_atleast_one_match_clause)
@pytest.mark.parametrize(
    "doc,some_expected,all_expected",
    [
        (
            {
                "Tags": [
                    {"Key": "InPROD", "Value": "ProdApp"},
                    {"Key": "NoP", "Value": "NoQ"},
                ]
            },
            "PASS",
            "FAIL",
        ),
        ({"Tags": []}, "FAIL", "FAIL"),
        ({}, "FAIL", "FAIL"),
    ],
)
def test_atleast_one_match_clause(doc, some_expected, all_expected):
    assert (
        _rule_status("rule r { some Tags[*].Key == /PROD/ }", doc, "r")
        == some_expected
    )
    assert (
        _rule_status("rule r { Tags[*].Key == /PROD/ }", doc, "r")
        == all_expected
    )


def test_atleast_one_match_selection_filter():
    doc = {
        "Resources": {
            "ddbSelected": {
                "Type": "AWS::DynamoDB::Table",
                "Properties": {
                    "Tags": [{"Key": "PROD", "Value": "ProdApp"}]
                },
            },
            "ddbNotSelected": {"Type": "AWS::DynamoDB::Table"},
        }
    }
    selected = _select(
        "Resources.*[ Type == 'AWS::DynamoDB::Table' "
        "some Properties.Tags[*].Key == /PROD/ ]",
        doc,
    )
    assert [v.path.s for v in selected] == ["/Resources/ddbSelected"]


# eval_context_tests.rs:409 (test_with_converter): lowercase query
# parts resolve against capitalized document keys via the case
# converters, and the non-matching resource UnResolves at its
# deepest reached value
def test_query_with_case_converters():
    from guard_tpu.core.qresult import UNRESOLVED

    doc = {
        "Resources": {
            "s3": {
                "Type": "AWS::S3::Bucket",
                "Properties": {"Tags": [{"Key": 1, "Value": 1}]},
            },
            "ec2": {
                "Type": "AWS::EC2::Instance",
                "Properties": {"ImageId": "ami-123456789012", "Tags": []},
            },
        }
    }
    rf = parse_rules_file(
        "let q = resources.*.properties.tags[*].value\nrule r { %q !empty }",
        "c.guard",
    )
    aq = rf.assignments[0].value
    scope = RootScope(rf, from_plain(doc))
    results = scope.query(aq.query)
    assert len(results) == 2
    resolved = [r for r in results if r.tag == RESOLVED]
    unresolved = [r for r in results if r.tag == UNRESOLVED]
    assert len(resolved) == 1 and len(unresolved) == 1
    assert resolved[0].value.path.s == "/Resources/s3/Properties/Tags/0/Value"
    assert (
        unresolved[0].unresolved.traversed_to.path.s
        == "/Resources/ec2/Properties/Tags"
    )
