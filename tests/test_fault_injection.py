"""Failure-plane suite: deterministic fault injection
(guard_tpu/utils/faults.py) driving document quarantine, ingest-worker
recovery, the packed-dispatch -> per-file -> host-oracle degradation
ladder, serve request isolation, and the `--max-doc-failures` exit
contract. Every degraded run must keep the UNAFFECTED documents
byte-identical to a clean run — a fault may cost throughput, never
correctness."""

import json

import pytest

from guard_tpu.cli import run
from guard_tpu.core.errors import GuardError
from guard_tpu.parallel import ingest
from guard_tpu.utils import faults
from guard_tpu.utils.io import Reader, Writer

RULES = (
    "let b = Resources.*[ Type == 'AWS::S3::Bucket' ]\n"
    "rule sse when %b !empty { %b.Properties.Enc == true }\n"
)


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    """Every test starts with no active faults, fresh counters and no
    cached worker pools (worker-side injection needs the env var set
    BEFORE the pool spawns), and instant retry backoff."""
    monkeypatch.delenv("GUARD_TPU_FAULT", raising=False)
    monkeypatch.setenv("GUARD_TPU_RETRY_BACKOFF", "0")
    faults.reset_faults()
    ingest.close_shared_pools()
    yield
    ingest.close_shared_pools()
    faults.reset_faults()


def _mk_corpus(tmp_path, n=6, fail=(2,), poison=False):
    rules = tmp_path / "rules.guard"
    rules.write_text(RULES)
    data = tmp_path / "data"
    data.mkdir(exist_ok=True)
    for i in range(n):
        doc = {
            "Resources": {
                "b": {
                    "Type": "AWS::S3::Bucket",
                    "Properties": {"Enc": i not in fail},
                }
            }
        }
        (data / f"t{i:02d}.json").write_text(json.dumps(doc))
    if poison:
        # sorts LAST so chunks holding the clean docs are unchanged
        (data / "zpoison.json").write_text("{not valid json")
    return rules, data


def _sweep(tmp_path, rules, data, *extra, tag="m", workers=0, chunk=3):
    w = Writer.buffered()
    rc = run(
        ["sweep", "-r", str(rules), "-d", str(data),
         "-M", str(tmp_path / f"{tag}.jsonl"), "-c", str(chunk),
         "--backend", "tpu", "--ingest-workers", str(workers), *extra],
        writer=w, reader=Reader(),
    )
    summary = json.loads(w.out.getvalue().strip().splitlines()[-1])
    summary.pop("manifest")
    return rc, summary


def _validate(rules, data, *extra):
    w = Writer.buffered()
    rc = run(
        ["validate", "-r", str(rules), "-d", str(data),
         "--backend", "tpu", *extra],
        writer=w, reader=Reader(),
    )
    return rc, w.out.getvalue(), w.err.getvalue()


# ---------------------------------------------------------------- specs


def test_fault_spec_parsing():
    assert faults._parse("read:nth=3") == {"read": {"nth": 3}}
    assert faults._parse("parse:glob=bad*,dispatch:nth=1") == {
        "parse": {"glob": "bad*"}, "dispatch": {"nth": 1},
    }
    assert faults._parse("oracle:rate=0.5:seed=s7") == {
        "oracle": {"rate": 0.5, "seed": "s7"},
    }
    with pytest.raises(GuardError):
        faults._parse("bogus_point:nth=1")
    with pytest.raises(GuardError):
        faults._parse("read:nth")  # not key=value
    with pytest.raises(GuardError):
        faults._parse("read:nth=x")
    with pytest.raises(GuardError):
        faults._parse("read:seed=1")  # needs nth/glob/rate


def test_nth_spec_fires_exactly_once(monkeypatch):
    monkeypatch.setenv("GUARD_TPU_FAULT", "dispatch:nth=2")
    faults.reset_faults()
    fired = [faults.should_fire("dispatch") for _ in range(5)]
    assert fired == [False, True, False, False, False]
    assert not faults.should_fire("collect")


def test_rate_spec_is_deterministic(monkeypatch):
    monkeypatch.setenv("GUARD_TPU_FAULT", "read:rate=0.4:seed=s1")

    def pattern():
        faults.reset_faults()
        return [
            faults.should_fire("read", key=f"doc{i}.json")
            for i in range(40)
        ]

    a, b = pattern(), pattern()
    assert a == b  # no wall-clock, no global RNG
    assert any(a) and not all(a)


def test_maybe_fail_counts_and_raises(monkeypatch):
    monkeypatch.setenv("GUARD_TPU_FAULT", "read:glob=bad*")
    faults.reset_faults()
    faults.maybe_fail("read", key="fine.json")  # no-op
    with pytest.raises(faults.InjectedFault):
        faults.maybe_fail("read", key="bad.json")
    assert faults.fault_stats()["injected_read"] == 1


# ---------------------------------------------- doc-stage quarantine


@pytest.mark.parametrize("workers", [0, 1, 2])
@pytest.mark.parametrize("stage", ["read", "parse", "encode"])
def test_doc_fault_quarantines_only_that_doc(
    tmp_path, monkeypatch, stage, workers
):
    """An injected read/parse/encode failure on one doc quarantines
    exactly that doc — counts, failed list and exit code for the rest
    of the corpus match a clean run without it."""
    rules, data = _mk_corpus(tmp_path)
    base_rc, base = _sweep(tmp_path, rules, data, tag=f"{stage}-base")
    # the victim sorts last: the chunks holding the clean docs are
    # byte-for-byte the same work in both runs
    (data / "zvictim.json").write_text(
        json.dumps({"Resources": {"b": {
            "Type": "AWS::S3::Bucket", "Properties": {"Enc": True}}}})
    )
    monkeypatch.setenv("GUARD_TPU_FAULT", f"{stage}:glob=zvictim*")
    faults.reset_faults()
    rc, summary = _sweep(
        tmp_path, rules, data, tag=f"{stage}-w{workers}", workers=workers
    )
    q = summary.pop("quarantined")
    assert [r["file"] for r in q] == ["zvictim.json"]
    assert q[0]["stage"] == stage
    assert q[0]["error"] == "InjectedFault"
    assert summary["counts"] == base["counts"]
    assert summary["failed"] == base["failed"]
    assert summary["documents"] == base["documents"] + 1
    assert rc == base_rc


def test_clean_run_summary_has_no_quarantine_key(tmp_path):
    rules, data = _mk_corpus(tmp_path)
    _rc, summary = _sweep(tmp_path, rules, data, tag="clean")
    assert "quarantined" not in summary


def test_max_doc_failures_exit_contract(tmp_path):
    """Default: doc failures degrade, never error. 0 restores
    fail-fast. N errors only above N quarantines; negative =
    unlimited."""
    rules, data = _mk_corpus(tmp_path, fail=(), poison=True)
    rc, summary = _sweep(tmp_path, rules, data, tag="dflt")
    assert rc == 0  # clean docs all pass; poison only quarantined
    assert [r["file"] for r in summary["quarantined"]] == ["zpoison.json"]
    rc0, _ = _sweep(tmp_path, rules, data, "--max-doc-failures", "0",
                    tag="df0")
    assert rc0 == 5
    rc1, _ = _sweep(tmp_path, rules, data, "--max-doc-failures", "1",
                    tag="df1")
    assert rc1 == 0
    rcn, _ = _sweep(tmp_path, rules, data, "--max-doc-failures", "-1",
                    tag="dfn")
    assert rcn == 0


def test_max_doc_failures_zero_without_faults_is_bit_exact(tmp_path):
    """`--max-doc-failures 0` over a clean corpus reproduces the
    default run exactly — the failure plane is free when unused."""
    rules, data = _mk_corpus(tmp_path)
    rc_a, sum_a = _sweep(tmp_path, rules, data, tag="pa")
    rc_b, sum_b = _sweep(tmp_path, rules, data, "--max-doc-failures",
                         "0", tag="pb")
    assert (rc_a, sum_a) == (rc_b, sum_b)


# ----------------------------------------------- worker crash recovery


def test_worker_crash_retries_chunk_and_restarts_pool(
    tmp_path, monkeypatch
):
    rules, data = _mk_corpus(tmp_path)
    base = _sweep(tmp_path, rules, data, tag="wc-base", workers=2)
    monkeypatch.setenv("GUARD_TPU_FAULT", "worker_crash:nth=1")
    ingest.close_shared_pools()
    faults.reset_faults()
    got = _sweep(tmp_path, rules, data, tag="wc-fault", workers=2)
    assert got == base  # the retried chunk reproduces exactly
    stats = faults.fault_stats()
    assert stats["injected_worker_crash"] == 1
    assert stats["retries"] >= 1
    assert stats["worker_restarts"] >= 1


# ------------------------------------------- dispatch/collect ladder


@pytest.mark.parametrize("pack", ["1", "0"], ids=["packed", "perfile"])
@pytest.mark.parametrize("point", ["dispatch", "collect"])
def test_device_fault_falls_back_to_host(
    tmp_path, monkeypatch, point, pack
):
    """A device dispatch/collect failure for one bucket degrades to
    the host oracle for just those docs — same counts, failed list and
    exit code as the clean run."""
    rules, data = _mk_corpus(tmp_path)
    monkeypatch.setenv("GUARD_TPU_PACK", pack)
    base = _sweep(tmp_path, rules, data, tag=f"{point}{pack}-base")
    monkeypatch.setenv("GUARD_TPU_FAULT", f"{point}:nth=1")
    faults.reset_faults()
    got = _sweep(tmp_path, rules, data, tag=f"{point}{pack}-fault")
    assert got == base
    assert faults.fault_stats()["dispatch_fallbacks"] >= 1


def test_oracle_fault_is_a_hard_error(tmp_path, monkeypatch):
    """The host oracle is the LAST rung: a failure there surfaces as a
    real evaluation error (nonzero exit), not silent data loss."""
    rules, data = _mk_corpus(tmp_path)
    monkeypatch.setenv("GUARD_TPU_FAULT", "oracle:nth=1")
    faults.reset_faults()
    w = Writer.buffered()
    rc = run(
        ["sweep", "-r", str(rules), "-d", str(data),
         "-M", str(tmp_path / "orc.jsonl"), "-c", "3",
         "--backend", "cpu"],
        writer=w, reader=Reader(),
    )
    summary = json.loads(w.out.getvalue().strip().splitlines()[-1])
    assert rc == 5
    assert summary["errors"] >= 1
    assert faults.fault_stats()["injected_oracle"] == 1


# --------------------------------------------- validate quarantine


def test_validate_default_still_fails_fast_on_poison(tmp_path):
    rules, data = _mk_corpus(tmp_path, fail=(), poison=True)
    rc, _out, _err = _validate(rules, data)
    assert rc == 5


@pytest.mark.parametrize(
    "mode",
    [
        [],
        ["-o", "yaml"],
        ["--structured", "-o", "json", "--show-summary", "none"],
        ["--structured", "-o", "junit", "--show-summary", "none"],
    ],
    ids=["console", "yaml", "json", "junit"],
)
def test_validate_quarantine_completes_and_excludes_doc(tmp_path, mode):
    rules, data = _mk_corpus(tmp_path, poison=True)
    rc, out, err = _validate(rules, data, "--max-doc-failures", "-1",
                             *mode)
    assert rc == 19  # t02 genuinely fails; poison only degrades
    assert "skipping zpoison.json" in err
    assert "zpoison" not in out
    rc0, _out, _err = _validate(rules, data, "--max-doc-failures", "0",
                                *mode)
    assert rc0 == 5


def test_validate_quarantine_clean_corpus_matches_default(tmp_path):
    """With no failing docs the quarantine encode path must reproduce
    the default batch-build chain byte-for-byte."""
    rules, data = _mk_corpus(tmp_path)
    base = _validate(rules, data, "--structured", "-o", "json",
                     "--show-summary", "none")
    got = _validate(rules, data, "--max-doc-failures", "5",
                    "--structured", "-o", "json", "--show-summary",
                    "none")
    assert got == base


# ----------------------------------------------- serve isolation


def test_serve_timeout_answers_and_keeps_serving(monkeypatch):
    import time

    from guard_tpu.commands import validate as validate_mod

    real_execute = validate_mod.Validate.execute

    def slow_execute(self, writer, reader):
        if self.verbose:  # the request marks itself slow
            time.sleep(1.0)
            return 0
        return real_execute(self, writer, reader)

    monkeypatch.setattr(validate_mod.Validate, "execute", slow_execute)
    monkeypatch.setenv("GUARD_TPU_SERVE_TIMEOUT", "0.2")
    w = Writer.buffered()
    reqs = [
        json.dumps({"rules": ["rule ok { a exists }"],
                    "data": ['{"a": 1}'], "verbose": True}),
        json.dumps({"rules": ["rule ok { a exists }"],
                    "data": ['{"a": 1}']}),
    ]
    rc = run(["serve", "--stdio"], writer=w,
             reader=Reader.from_string("\n".join(reqs) + "\n"))
    assert rc == 0
    resps = [json.loads(l) for l in w.out.getvalue().splitlines()
             if l.strip()]
    assert resps[0]["code"] == 5
    assert resps[0]["error_class"] == "RequestTimeout"
    assert "0.2" in resps[0]["error"]
    assert resps[1]["code"] == 0  # the session outlives the timeout


def test_serve_error_response_names_exception_class():
    w = Writer.buffered()
    rc = run(["serve", "--stdio"], writer=w,
             reader=Reader.from_string("[1, 2, 3]\n\n"))
    assert rc == 0
    resp = json.loads(w.out.getvalue().splitlines()[0])
    assert resp["code"] == 5
    assert resp["error_class"] == "ValueError"


def test_serve_metrics_request_returns_live_snapshot():
    """A {"metrics": true} request answers with the same schema-
    versioned snapshot --metrics-out writes, including the persistent
    per-request latency histogram covering the preceding requests."""
    from guard_tpu.utils import telemetry

    telemetry.REGISTRY.reset(include_persistent=True)
    w = Writer.buffered()
    reqs = [
        json.dumps({"rules": ["rule ok { a exists }"],
                    "data": ['{"a": 1}']}),
        json.dumps({"metrics": True}),
    ]
    rc = run(["serve", "--stdio"], writer=w,
             reader=Reader.from_string("\n".join(reqs) + "\n"))
    assert rc == 0
    resps = [json.loads(l) for l in w.out.getvalue().splitlines()
             if l.strip()]
    assert resps[0]["code"] == 0
    m = resps[1]
    assert m["code"] == 0
    snap = m["metrics"]
    assert snap["schema_version"] == telemetry.SCHEMA_VERSION
    for section in ("counters", "gauges", "histograms", "spans"):
        assert section in snap
    # the latency histogram is persistent: the validate request's
    # reset_all_stats switch must not have erased it
    lat = snap["histograms"]["serve_request_seconds"]
    assert lat["count"] == 1  # the validate request before this one
    assert lat["p50_seconds"] is not None
    telemetry.REGISTRY.reset(include_persistent=True)


def test_serve_timeout_leaves_annotated_span_and_counters(monkeypatch):
    """The failure plane is faithful in the trace: a timed-out request
    leaves a serve_request span annotated RequestTimeout, and the
    persistent latency histogram still counts the abandoned request."""
    import time

    from guard_tpu.commands import validate as validate_mod
    from guard_tpu.utils import telemetry

    real_execute = validate_mod.Validate.execute

    def slow_execute(self, writer, reader):
        if self.verbose:
            time.sleep(1.0)
            return 0
        return real_execute(self, writer, reader)

    monkeypatch.setattr(validate_mod.Validate, "execute", slow_execute)
    monkeypatch.setenv("GUARD_TPU_SERVE_TIMEOUT", "0.2")
    telemetry.REGISTRY.reset(include_persistent=True)
    telemetry.enable()
    telemetry.reset_trace()
    try:
        w = Writer.buffered()
        reqs = [
            json.dumps({"rules": ["rule ok { a exists }"],
                        "data": ['{"a": 1}'], "verbose": True}),
            json.dumps({"rules": ["rule ok { a exists }"],
                        "data": ['{"a": 1}']}),
        ]
        rc = run(["serve", "--stdio"], writer=w,
                 reader=Reader.from_string("\n".join(reqs) + "\n"))
        assert rc == 0
        spans = [r for r in telemetry._TRACE
                 if r["name"] == "serve_request"]
        assert len(spans) == 2
        timed_out = [r for r in spans
                     if r.get("attrs", {}).get("error_class")
                     == "RequestTimeout"]
        assert len(timed_out) == 1
        # counters survive the abandoned worker thread: both requests
        # (the timed-out one included) landed in the latency histogram
        lat = telemetry.REGISTRY.histogram("serve_request_seconds")
        assert lat.count == 2
    finally:
        telemetry.disable()
        telemetry.reset_trace()
        telemetry.REGISTRY.reset(include_persistent=True)


# ------------------------------------------ spawn-probe failure cache


def test_spawn_probe_failure_cached_once(tmp_path, monkeypatch, caplog):
    """A failed worker spawn is probed AT MOST once per process: later
    sweeps skip the probe (and its ping timeout) and warn exactly
    once. restart_shared_pool clears the mark."""
    calls = []

    def boom(workers):
        calls.append(workers)
        raise OSError("spawn blocked for test")

    ingest.close_shared_pools()
    monkeypatch.setattr(ingest, "_spawn_pool", boom)
    import logging

    with caplog.at_level(logging.WARNING, logger=ingest.log.name):
        assert ingest.shared_pool(2) is None
        assert ingest.shared_pool(2) is None
        assert ingest.shared_pool(2) is None
    assert len(calls) == 1  # probe paid once, failure cached
    warns = [r for r in caplog.records
             if "spawn blocked for test" in r.getMessage()]
    assert len(warns) == 1  # warned exactly once
    # deliberate recovery clears the mark and probes again
    assert ingest.restart_shared_pool(2) is None
    assert len(calls) == 2
    ingest.close_shared_pools()
