"""Static-analysis plane: plan/IR verifier suite
(guard_tpu/analysis/verify.py + its ops/plan.py hooks).

The core of the suite is mutation testing: seed each corruption class
the verifier promises to catch (swapped segment offsets, truncated bit
tables, off-by-one anchor-chain slots, stale intern ids, rim spec
drift, dangling slot references) into a healthy plan and assert the
violation comes back under its *named* invariant — plus the other
half of the bargain: the unmutated plan, including one lowered from
the full shipped corpus, verifies clean before AND after relocation.

Policy hooks: a corrupt artifact on *load* degrades to a cache miss
whose warning names the violated invariant (cause=verify:<name>) and
bumps the plan_cache corrupt_verify counter; the same corruption on a
*fresh* lowering raises PlanVerifyError (exit-5 hard diagnostic).
GUARD_TPU_ANALYSIS=0 and verify=False both skip the checks.
"""

import pickle
from pathlib import Path

import numpy as np
import pytest

from guard_tpu.analysis import analysis_stats, reset_analysis_stats
from guard_tpu.analysis.verify import (
    INVARIANTS,
    PlanVerifyError,
    first_violation_name,
    verify_plan,
    verify_relocation,
)
from guard_tpu.commands.validate import RuleFile
from guard_tpu.core.parser import parse_rules_file
from guard_tpu.core.values import from_plain
from guard_tpu.ops import plan as plan_mod
from guard_tpu.ops.encoder import Interner, encode_batch
from guard_tpu.ops.ir import StepKeyChain

REPO = Path(__file__).resolve().parent.parent

# the nested literal path Properties.Enc folds into a StepKeyChain, so
# the chain invariants are live on this tiny registry
RULES_A = (
    "let b = Resources.*[ Type == 'AWS::S3::Bucket' ]\n"
    "rule sse when %b !empty { %b.Properties.Enc == true }\n"
)
RULES_B = (
    "rule named { Resources.*.Properties.Name in ['web', 'db'] }\n"
    "rule arnish { Resources.*.Properties.Arn == /^arn:aws:/ }\n"
)


def _rule_file(content: str, name: str = "r.guard") -> RuleFile:
    return RuleFile(
        name=name, full_name=name, content=content,
        rules=parse_rules_file(content, name),
    )


@pytest.fixture(autouse=True)
def _fresh_plan_state(tmp_path, monkeypatch):
    monkeypatch.setenv("GUARD_TPU_PLAN_CACHE_DIR", str(tmp_path / "plans"))
    plan_mod.clear_plan_memo()
    plan_mod.reset_plan_stats()
    reset_analysis_stats()
    yield
    plan_mod.clear_plan_memo()
    plan_mod.reset_plan_stats()
    reset_analysis_stats()


def _build():
    return plan_mod.get_plan([_rule_file(RULES_A, "a.guard"),
                              _rule_file(RULES_B, "b.guard")])


def _relocate(plan, doc=None):
    doc = doc or {
        "Resources": {"x": {"Type": "AWS::S3::Bucket",
                            "Properties": {"Enc": True, "Name": "web"}}}
    }
    chunk = Interner()
    batch, _ = encode_batch([from_plain(doc)], chunk)
    plan_mod.relocate_batch(plan, batch, chunk)
    return batch


def _names(violations):
    return {v.invariant for v in violations}


def _pack_chain(plan):
    """First folded StepKeyChain in the plan's pack (the fixture rules
    guarantee one exists)."""
    _pos, packed, _spec = plan.packs[0]
    found = []

    def visit(s):
        if isinstance(s, StepKeyChain):
            found.append(s)

    from guard_tpu.analysis.verify import _walk_compiled
    _walk_compiled(packed.compiled, visit, lambda n: None)
    assert found, "fixture rules must fold at least one key chain"
    return found[0]


# ------------------------------------------------------ healthy plans


def test_fresh_plan_verifies_clean():
    plan = _build()
    assert verify_plan(plan) == []
    stats = analysis_stats()
    assert stats["invariants_checked"] > 0
    assert stats["violations"] == 0


def test_relocated_plan_verifies_clean():
    plan = _build()
    batch = _relocate(plan)
    assert verify_plan(plan) == []
    assert verify_relocation(plan, batch) == []


def test_full_corpus_plan_verifies_clean():
    """The whole shipped corpus lowers to a plan with zero violations,
    before and after a relocation — the no-false-positive half of the
    mutation bargain."""
    rule_files = []
    for p in sorted((REPO / "corpus" / "rules").glob("*.guard")):
        rf = parse_rules_file(p.read_text(), p.name)
        if rf is not None:
            rule_files.append(RuleFile(name=p.name, full_name=str(p),
                                       content=p.read_text(), rules=rf))
    assert len(rule_files) > 100
    plan = plan_mod.build_plan(rule_files)
    assert verify_plan(plan) == []
    batch = _relocate(plan)
    assert verify_plan(plan) == []
    assert verify_relocation(plan, batch) == []


# --------------------------------------------------- seeded mutations


def test_mutation_swapped_segment_offsets():
    plan = _build()
    _pos, packed, _spec = plan.packs[0]
    assert len(packed.offsets) >= 2
    packed.offsets[0], packed.offsets[1] = (packed.offsets[1],
                                            packed.offsets[0])
    assert "segment_offsets_consistent" in _names(verify_plan(plan))


def test_mutation_truncated_bit_table():
    plan = _build()
    batch = _relocate(plan)  # grow the tables past zero width first
    part = plan.packs[0][1].compiled
    assert part.bit_tables and len(part.bit_tables[0][0]) > 0
    table, target = part.bit_tables[0]
    part.bit_tables[0] = (table[:-1], target)
    violations = verify_plan(plan)
    assert "bit_table_width" in _names(violations)
    # the cheap per-chunk subset catches it too
    assert "bit_table_width" in _names(verify_relocation(plan, batch))


def test_mutation_off_by_one_chain_slot():
    plan = _build()
    chain = _pack_chain(plan)
    chain.chain_slot += 1
    assert "anchor_chain_domains" in _names(verify_plan(plan))


def test_mutation_chain_spec_drift():
    """chain_slot still in range, but the bound spec no longer matches
    the folded steps — the anchor columns would be computed for the
    wrong keys."""
    plan = _build()
    chain = _pack_chain(plan)
    comp = plan.packs[0][1].compiled
    spec = comp.chain_tables[chain.chain_slot]
    comp.chain_tables[chain.chain_slot] = (
        (("NotTheKey",), spec[0][1]),) + tuple(spec[1:])
    assert "anchor_chain_domains" in _names(verify_plan(plan))


def test_mutation_stale_intern_ids():
    plan = _build()
    batch = _relocate(plan)
    batch.scalar_id = batch.scalar_id.copy()
    batch.scalar_id.flat[0] = len(plan.interner.strings) + 7
    violations = verify_relocation(plan, batch)
    assert _names(violations) == {"intern_id_domain"}


def test_mutation_rim_spec_drift():
    plan = _build()
    _pos, _packed, spec = plan.packs[0]
    spec.group_ids = np.roll(spec.group_ids, 1)
    assert "rim_name_group_coverage" in _names(verify_plan(plan))


def test_mutation_dangling_slot_reference():
    plan = _build()
    part = plan.packs[0][1].compiled
    # orphan every bit-table slot reference by dropping the tables
    part.bit_tables = []
    part.bit_specs = []
    assert "slot_relocation_bijective" in _names(verify_plan(plan))


def test_every_emitted_name_is_catalogued():
    """Whatever the mutations above produce must come from the
    published INVARIANTS tuple (docs enumerate against it)."""
    plan = _build()
    plan.packs[0][1].offsets[0] += 1
    for v in verify_plan(plan):
        assert v.invariant in INVARIANTS
    assert first_violation_name([]) is None


# ------------------------------------------------------- policy hooks


def _corrupt_saved_artifact(plan):
    """Rewrite the on-disk artifact with a seeded chain-slot
    corruption, keeping schema/version/digest valid so only the
    verifier can reject it."""
    art = plan_mod._artifact_path(plan.digest)
    payload = pickle.loads(art.read_bytes())
    from guard_tpu.analysis.verify import _walk_compiled

    found = []

    def visit(s):
        if isinstance(s, StepKeyChain):
            found.append(s)

    _walk_compiled(payload["plan"].packs[0][1].compiled, visit,
                   lambda n: None)
    found[0].chain_slot += 1
    art.write_bytes(pickle.dumps(payload))


def test_corrupt_artifact_load_is_named_miss(caplog):
    plan = _build()
    _corrupt_saved_artifact(plan)
    plan_mod.clear_plan_memo()
    plan_mod.reset_plan_stats()
    with caplog.at_level("WARNING", logger="guard_tpu.plan"):
        assert plan_mod.load_plan(plan.digest) is None
    msgs = [r.getMessage() for r in caplog.records]
    assert any("cause=verify:anchor_chain_domains" in m for m in msgs)
    assert plan_mod.plan_stats()["corrupt_verify"] == 1
    # ... and get_plan rebuilds + rewrites a healthy artifact over it
    rebuilt = plan_mod.get_plan([_rule_file(RULES_A, "a.guard"),
                                 _rule_file(RULES_B, "b.guard")])
    assert verify_plan(rebuilt) == []
    assert plan_mod.plan_stats()["misses"] == 1


def test_corrupt_artifact_load_skipped_when_disabled(monkeypatch):
    plan = _build()
    _corrupt_saved_artifact(plan)
    plan_mod.clear_plan_memo()
    monkeypatch.setenv("GUARD_TPU_ANALYSIS", "0")
    # escape hatch: the (structurally loadable) artifact is accepted
    assert plan_mod.load_plan(plan.digest) is not None
    monkeypatch.delenv("GUARD_TPU_ANALYSIS")
    assert plan_mod.load_plan(plan.digest) is None  # verifier back on
    assert plan_mod.load_plan(plan.digest, verify=False) is not None


def test_fresh_lowering_violation_is_hard_error(monkeypatch):
    """A plan that fails verification straight out of build_plan is a
    miscompile in THIS process: get_plan must raise, not cache it."""
    real_build = plan_mod.build_plan

    def sabotaged(rule_files):
        plan = real_build(rule_files)
        plan.packs[0][1].offsets[0] += 1
        return plan

    monkeypatch.setattr(plan_mod, "build_plan", sabotaged)
    with pytest.raises(PlanVerifyError) as ei:
        _build()
    assert "segment_offsets_consistent" in str(ei.value)
    assert ei.value.violations


def test_relocation_violation_is_hard_error():
    """The exact bug class the per-chunk verify exists for: a batch
    whose id columns belong to a different interner than the one the
    caller passed. The remap is a no-op, the chunk-local ids leak into
    the plan namespace, and the gather would read garbage bit-table
    rows — relocate_batch must raise instead."""
    plan = _build()
    chunk = Interner()
    batch, _ = encode_batch(
        [from_plain({"Resources": {"x": {"Properties": {"Name": "web"}}}})],
        chunk,
    )
    with pytest.raises(PlanVerifyError) as ei:
        plan_mod.relocate_batch(plan, batch, Interner())  # wrong interner
    assert "intern_id_domain" in str(ei.value)


# ------------------------------------------- signatures in the artifact


RULES_T = (
    "rule typed {\n"
    "    Resources.*.Type == 'AWS::S3::Bucket'\n"
    "    Resources.*.Properties.Enc == true\n"
    "}\n"
)


def test_signatures_round_trip_through_artifact():
    rfs = [_rule_file(RULES_T, "t.guard"), _rule_file(RULES_B, "b.guard")]
    plan = plan_mod.get_plan(rfs)
    assert plan.signatures is not None
    sigs = plan.signatures
    assert len(sigs.files) == 2
    # t.guard anchors on the S3 bucket type equality; both files on
    # the Resources key chain
    assert "AWS::S3::Bucket" in sigs.files[0].type_equalities
    assert ("Resources",) in sigs.files[1].key_chains
    assert sigs.files[0].unanchored_rules == 0

    plan_mod.clear_plan_memo()
    reloaded = plan_mod.get_plan([_rule_file(RULES_T, "t.guard"),
                                  _rule_file(RULES_B, "b.guard")])
    assert plan_mod.plan_stats()["hits"] == 1
    assert reloaded.signatures is not None
    assert reloaded.signatures.files[0].to_json() == sigs.files[0].to_json()

    # the digest-versioned sidecar rides beside the pickle with the
    # pack inverted index
    import json

    sidecar = plan_mod.plan_cache_dir() / f"{plan.digest}.sigs.json"
    doc = json.loads(sidecar.read_text())
    assert doc["digest"] == plan.digest
    assert doc["packs"] and "members" in doc["packs"][0]
    assert doc["packs"][0]["type_equalities"]
