"""Differential tests for host-precomputed function variables
(ops/fnvars.py): file-level function `let`s are resolved per document
on the host and encoded as orphan result subtrees the kernels select
via StepFnVar. Statuses must match the CPU oracle bit-for-bit;
`now`/`parse_char` (excluded) must keep their rules on the host."""

import pytest

from guard_tpu.core.parser import parse_rules_file
from guard_tpu.core.scopes import RootScope
from guard_tpu.core.evaluator import eval_rules_file
from guard_tpu.core.values import from_plain
from guard_tpu.ops.encoder import encode_batch
from guard_tpu.ops.fnvars import precompute_fn_values, precomputable_fn_vars
from guard_tpu.ops.ir import compile_rules_file
from guard_tpu.ops.kernels import BatchEvaluator

STATUS = {0: "PASS", 1: "FAIL", 2: "SKIP"}


def _oracle(rf, doc):
    from guard_tpu.commands.report import rule_statuses_from_root

    scope = RootScope(rf, doc)
    eval_rules_file(rf, scope, None)
    root = scope.reset_recorder().extract()
    return {n: s.value for n, s in rule_statuses_from_root(root).items()}


def _differential(rules_text, docs_plain, expect_host=0, allow_unsure=False):
    rf = parse_rules_file(rules_text, "fn.guard")
    docs = [from_plain(d) for d in docs_plain]
    fn_vars, fn_vals, fn_err = precompute_fn_values(rf, docs)
    assert not fn_err, "unexpected function errors in differential docs"
    batch, interner = encode_batch(
        docs, fn_values=fn_vals, fn_var_order=fn_vars
    )
    compiled = compile_rules_file(rf, interner)
    assert len(compiled.host_rules) == expect_host, [
        r.rule_name for r in compiled.host_rules
    ]
    if not compiled.rules:
        return
    evaluator = BatchEvaluator(compiled)
    statuses = evaluator(batch)
    unsure = evaluator.last_unsure
    for di, doc in enumerate(docs):
        oracle = _oracle(rf, doc)
        for ri, crule in enumerate(compiled.rules):
            if unsure is not None and bool(unsure[di, ri]):
                assert allow_unsure, "unexpected unsure flag"
                continue
            dev = STATUS[int(statuses[di, ri])]
            assert dev == oracle[crule.name], (
                f"doc {di} ({docs_plain[di]}) rule {crule.name}: "
                f"device={dev} oracle={oracle[crule.name]}"
            )


DOCS = [
    {
        "Resources": {
            "a": {"Name": "Prod-Logs", "Size": "42", "Flag": "true",
                  "Blob": '{"x": 1, "y": [2, 3]}',
                  "When": "2024-01-02T03:04:05Z"},
            "b": {"Name": "dev-scratch", "Size": "7", "Flag": "false",
                  "Blob": '{"x": 9}',
                  "When": "2030-06-01T00:00:00Z"},
        }
    },
    {
        "Resources": {
            "a": {"Name": "QA-Box", "Size": "100.5", "Flag": "true",
                  "Blob": "[1, 2]", "When": "1999-12-31T23:59:59Z"}
        }
    },
    {"Other": 1},
]


def test_to_upper_lower_eq_and_regex():
    _differential(
        """
let upper = to_upper(Resources.*.Name)
let lower = to_lower(Resources.*.Name)

rule has_prod when Resources exists {
    some %upper == /PROD/
}
rule all_lower_lc when Resources exists {
    %lower == /^[a-z0-9-]+$/
}
rule upper_exact when Resources exists {
    some %upper == 'PROD-LOGS'
}
""",
        DOCS,
    )


def test_parse_int_float_bool_ordering():
    _differential(
        """
let sizes = parse_float(Resources.*.Size)
let flags = parse_boolean(Resources.*.Flag)

rule big when Resources exists { some %sizes > 40.0 }
rule all_small when Resources exists { %sizes < 1000.0 }
rule any_on when Resources exists { some %flags == true }
""",
        DOCS,
    )


def test_join_and_substring():
    _differential(
        """
let names = Resources.*.Name
let n = count(%names)
let joined = join(%names, ',')
let prefix = substring(%names, 0, 3)

rule joined_has_comma when %n >= 2 { %joined == /,/ }
rule prefix_checks when Resources exists { some %prefix == /^(Pro|dev|QA-)$/ }
""",
        # join raises on UnResolved args (IncompatibleError,
        # strings.rs join) — docs here always resolve Name
        [DOCS[0], DOCS[1]],
    )


def test_regex_replace_and_url_decode():
    _differential(
        """
let renamed = regex_replace(Resources.*.Name, '^(\\w+)-(\\w+)$', '${2}_${1}')

rule swapped when Resources exists { some %renamed == 'Logs_Prod' }
""",
        DOCS,
    )


def test_json_parse_subtree_walk():
    # json_parse results are TREES: walking into them uses ordinary
    # key steps over the orphan subtree
    _differential(
        """
let parsed = json_parse(Resources.*.Blob)

rule x_is_one when Resources exists { some %parsed.x == 1 }
rule y_second when Resources exists { some %parsed.y[1] == 3 }
""",
        DOCS,
    )


def test_parse_epoch_range():
    _differential(
        """
let when = parse_epoch(Resources.*.When)

rule before_2026 when Resources exists {
    some %when < 1767225600
}
""",
        DOCS,
    )


def test_chained_function_lets():
    _differential(
        """
let upper = to_upper(Resources.*.Name)
let swapped = regex_replace(%upper, '^(\\w+)-(\\w+)$', '$2/$1')

rule chained when Resources exists { some %swapped == 'LOGS/PROD' }
""",
        DOCS,
    )


def test_fn_var_inside_filter_broadcasts():
    _differential(
        """
let upper = to_upper(Resources.*.Name)

rule gated when Resources exists {
    Resources.*[ Size exists ] {
        some %upper == /PROD/
        Size exists
    }
}
""",
        DOCS,
    )


def test_fn_var_as_query_rhs():
    _differential(
        """
let upper = to_upper(Resources.*.Name)

rule in_upper when Resources exists {
    Resources.*.AllCaps IN %upper
}
""",
        [
            {"Resources": {"a": {"Name": "prod", "AllCaps": "PROD"}}},
            {"Resources": {"a": {"Name": "prod", "AllCaps": "DEV"}}},
        ],
    )


def test_fn_var_interpolation():
    _differential(
        """
let keyname = to_lower(Settings.Key)

rule has_key when Settings exists { Resources.%keyname exists }
""",
        [
            {"Settings": {"Key": "ALPHA"}, "Resources": {"alpha": 1}},
            {"Settings": {"Key": "BETA"}, "Resources": {"alpha": 1}},
        ],
    )


def test_fn_var_empty_results():
    _differential(
        """
let upper = to_upper(Resources.*.Missing)

rule any_upper when Resources exists { %upper !empty }
rule empty_upper when Resources exists { %upper empty }
""",
        DOCS,
    )


def test_now_and_parse_char_stay_host():
    rf = parse_rules_file(
        """
let t = now()
let c = parse_char(Resources.*.Digit)

rule time_ok when Resources exists { %t > 0 }
rule char_ok when Resources exists { %c exists }
""",
        "x.guard",
    )
    assert precomputable_fn_vars(rf) == []
    docs = [from_plain({"Resources": {"a": {"Digit": "5"}}})]
    batch, interner = encode_batch(docs)
    compiled = compile_rules_file(rf, interner)
    assert {r.rule_name for r in compiled.host_rules} == {"time_ok", "char_ok"}


def test_excluded_transitively_through_var_refs():
    rf = parse_rules_file(
        """
let t = now()
let u = to_upper(%t)
let ok = to_upper(Resources.*.Name)

rule r1 when Resources exists { %u exists }
rule r2 when Resources exists { %ok exists }
""",
        "x.guard",
    )
    assert precomputable_fn_vars(rf) == [("fn", -1, "ok", 0)]


def test_fn_error_doc_reported():
    # parse_int on an unparseable string raises on the oracle; the
    # precompute pass must flag the doc instead of crashing
    rf = parse_rules_file(
        """
let n = parse_int(Resources.*.Size)

rule ok when Resources exists { some %n >= 0 }
""",
        "x.guard",
    )
    docs = [
        from_plain({"Resources": {"a": {"Size": "42"}}}),
        from_plain({"Resources": {"a": {"Size": "not-a-number"}}}),
    ]
    fn_vars, fn_vals, fn_err = precompute_fn_values(rf, docs)
    assert fn_vars == [("fn", -1, "n", 0)]
    assert fn_err == {1}
    assert fn_vals[0][("fn", -1, "n", 0)][0].val == 42


def test_backend_cli_fn_parity(tmp_path):
    """End to end through `validate --backend tpu` vs the CPU path."""
    import subprocess
    import sys

    rules = tmp_path / "r.guard"
    rules.write_text(
        """
let upper = to_upper(Resources.*.Name)

rule named_prod when Resources exists { some %upper == /PROD/ }
"""
    )
    good = tmp_path / "good.json"
    good.write_text('{"Resources": {"a": {"Name": "prod-x"}}}')
    bad = tmp_path / "bad.json"
    bad.write_text('{"Resources": {"a": {"Name": "dev-x"}}}')
    rcs = {}
    for backend in ("tpu", "cpu"):
        for df in (good, bad):
            args = [sys.executable, "-m", "guard_tpu.cli", "validate",
                    "-r", str(rules), "-d", str(df)]
            if backend == "tpu":
                args += ["--backend", "tpu"]
            proc = subprocess.run(args, capture_output=True, text=True,
                                  timeout=300)
            rcs[(backend, df.name)] = proc.returncode
    assert rcs[("tpu", "good.json")] == rcs[("cpu", "good.json")] == 0
    assert rcs[("tpu", "bad.json")] == rcs[("cpu", "bad.json")] == 19


def test_rule_body_function_lets():
    """Rule-body function lets (the reference's join.guard /
    converters.guard / string_manipulation.guard fixture shape)."""
    _differential(
        """
let template = Resources.*[ Type == 'Svc' ]

rule SOME_RULE when %template !empty {
    let collection = %template.Collection.*
    let res = join(%collection, ",")
    %res == "a,b,c"
}

rule CONVERT when %template !empty {
    let minv = parse_int(%template.Min)
    %minv == 1
    let lower = to_lower(%template.Name)
    %lower == /^svc/
}
""",
        [
            {
                "Resources": {
                    "x": {
                        "Type": "Svc",
                        "Collection": {"p": "a", "q": "b", "r": "c"},
                        "Min": "1",
                        "Name": "SVC-MAIN",
                    }
                }
            },
            {
                "Resources": {
                    "x": {
                        "Type": "Svc",
                        "Collection": {"p": "a"},
                        "Min": "2",
                        "Name": "OTHER",
                    }
                }
            },
            {"Resources": {"y": {"Type": "Other"}}},
        ],
    )


def test_rule_body_json_parse_block_walk():
    """The reference's json_parse.guard inner shape: parse a policy
    string in the rule body and walk the parsed tree with a block."""
    _differential(
        """
let template = Resources.*[ Type == 'Svc' ]

rule SOME_RULE when %template !empty {
    let policy = %template.PolicyText
    let res = json_parse(%policy)

    %res !empty

    %res.Statement[*] {
        Effect == "Deny"
        Resource == "arn:aws:s3:::s3-test-123/*"
    }
}
""",
        [
            {
                "Resources": {
                    "x": {
                        "Type": "Svc",
                        "PolicyText": '{"Statement": [{"Effect": "Deny", "Resource": "arn:aws:s3:::s3-test-123/*"}]}',
                    }
                }
            },
            {
                "Resources": {
                    "x": {
                        "Type": "Svc",
                        "PolicyText": '{"Statement": [{"Effect": "Allow", "Resource": "arn:aws:s3:::s3-test-123/*"}]}',
                    }
                }
            },
            {"Resources": {"y": {"Type": "Other"}}},
        ],
    )


def test_rule_body_fn_shadows_file_fn():
    _differential(
        """
let name = to_upper(Settings.A)

rule shadowed when Settings exists {
    let name = to_upper(Settings.B)
    some %name == 'BEE'
}
rule unshadowed when Settings exists {
    some %name == 'AYE'
}
""",
        [
            {"Settings": {"A": "aye", "B": "bee"}},
            {"Settings": {"A": "bee", "B": "aye"}},
        ],
    )


def test_inline_fn_rhs_clause():
    """The reference's join_with_message.guard shape: a function call
    inline as clause RHS (the LHS string literal parses as a key
    query, which UnResolves)."""
    _differential(
        """
let template = Resources.*[ Type == 'Svc' ]

rule TEST_COLLECTION when %template !empty {
    let collection = %template.Collection.*
    let res = join(%collection, ",")
    %res == "a,b"
    "a,b" == join(%collection, ",")
}
""",
        [
            {"Resources": {"x": {"Type": "Svc", "Collection": {"p": "a", "q": "b"}}}},
            {"Resources": {"x": {"Type": "Svc", "Collection": {"p": "z"}}}},
            {"Resources": {"y": {"Type": "Other"}}},
        ],
    )


def test_literal_map_head_vs_fn_rhs():
    """The reference's json_parse.guard shape: a literal-map let used
    as a query head, compared against json_parse results."""
    _differential(
        """
let template = Resources.*[ Type == 'Svc' ]

let expected = {
    "Principal": "*",
    "Actions": ["s3*", "ec2*"]
}

rule SOME_RULE when %template !empty {
    let policy = %template.Policy
    let res = json_parse(%policy)

    %expected == json_parse(%policy)
    %res !empty
    %res == %expected
}
""",
        [
            {
                "Resources": {
                    "x": {
                        "Type": "Svc",
                        "Policy": '{"Principal": "*", "Actions": ["s3*", "ec2*"]}',
                    }
                }
            },
            {
                "Resources": {
                    "x": {
                        "Type": "Svc",
                        "Policy": '{"Principal": "admin", "Actions": []}',
                    }
                }
            },
            {"Resources": {"y": {"Type": "Other"}}},
        ],
    )


def test_parameterized_call_with_fn_args():
    """The reference's complex_rules.guard shapes: count() and
    regex_replace() as parameterized-rule-call arguments."""
    _differential(
        """
rule compare_number_of_buckets(expected) {
    %expected == 2
}

rule compare_replaced(replaced, expected) {
    %replaced == %expected
}

let buckets = Resources.*[ Type == 'Bucket' ]

rule COMBINED when %buckets !empty {
    compare_number_of_buckets(count(%buckets))
}

rule WITH_REGEX when %buckets exists {
    let arn = %buckets.Arn
    let expected = "aws/123/us-west-2"
    compare_replaced(regex_replace(%arn, "^arn:(\\w+):(\\d+):([\\w0-9-]+)$", "${1}/${2}/${3}"), %expected)
}
""",
        [
            {
                "Resources": {
                    "a": {"Type": "Bucket", "Arn": "arn:aws:123:us-west-2"},
                    "b": {"Type": "Bucket", "Arn": "arn:aws:123:us-west-2"},
                }
            },
            {
                "Resources": {
                    "a": {"Type": "Bucket", "Arn": "arn:aws:999:eu-west-1"}
                }
            },
            {"Resources": {"y": {"Type": "Other"}}},
        ],
    )


def test_literal_head_walk_into_subtree():
    _differential(
        """
let expected = { "a": {"b": [1, 2]} }

rule walk when Resources exists {
    %expected.a.b[1] == 2
    %expected.a.b[0] == Resources.First
}
""",
        [
            {"Resources": {"First": 1}},
            {"Resources": {"First": 7}},
        ],
    )


def test_literal_call_arg_as_callee_head():
    """The reference's failing_complex_rule.guard shape: a string
    literal passed as a call argument and used as a query head in the
    callee."""
    _differential(
        """
rule compare_replaced(replaced, expected) {
    %expected == %replaced
}

let svcs = Resources.*[ Type == 'Svc' ]

rule CALLS when %svcs exists {
    let arn = %svcs.Arn
    compare_replaced(regex_replace(%arn, "^arn:(\\w+):(\\d+)$", "${1}/${2}"), "aws/123")
}
""",
        [
            {"Resources": {"a": {"Type": "Svc", "Arn": "arn:aws:123"}}},
            {"Resources": {"a": {"Type": "Svc", "Arn": "arn:aws:999"}}},
            {"Resources": {"y": {"Type": "Other"}}},
        ],
    )


def test_same_fn_let_in_two_when_blocks():
    """Round 5 (VERDICT r4 item 5): the same function-let NAME bound in
    TWO root-basis when blocks lowers — each binding gets its own
    precompute slot keyed by the binding's FunctionExpr identity, and
    the when-block scoping resolves shadowing exactly like the oracle."""
    _differential(
        """
rule r {
    when Resources exists {
        let u = to_upper(Resources.*.Name)
        some %u == 'ALPHA'
    }
    when Outputs exists {
        let u = to_upper(Outputs.*.Name)
        some %u == 'BETA'
    }
}
""",
        [
            {"Resources": {"a": {"Name": "alpha"}},
             "Outputs": {"o": {"Name": "beta"}}},
            {"Resources": {"a": {"Name": "alpha"}}},
            {"Outputs": {"o": {"Name": "nope"}}},
            {"Other": 1},
        ],
    )


def test_fn_let_shadows_file_let_across_when_blocks():
    """Shadowing: a when-block binding must win over the file-level
    binding of the same name inside its block, and the file binding
    must win outside."""
    _differential(
        """
let u = to_upper(Resources.*.Kind)

rule outer when Resources exists { some %u == 'FILE' }
rule inner {
    when Resources exists {
        let u = to_lower(Resources.*.Name)
        some %u == 'block'
    }
}
""",
        [
            {"Resources": {"a": {"Kind": "file", "Name": "BLOCK"}}},
            {"Resources": {"a": {"Kind": "other", "Name": "nope"}}},
        ],
    )


def test_nested_when_blocks_same_name_three_bindings():
    """Three bindings of one name across body + nested whens: every
    use site resolves its innermost binding's slot."""
    _differential(
        """
rule r {
    let u = to_upper(Resources.*.Tag)
    some %u == 'BODY'
    when Resources exists {
        let u = to_upper(Resources.*.Name)
        some %u == 'WHEN1'
        when Resources exists {
            let u = to_lower(Resources.*.Name)
            some %u == 'when2'
        }
    }
}
""",
        [
            {"Resources": {"a": {"Tag": "body", "Name": "When1"}}},
            {"Resources": {"a": {"Tag": "body", "Name": "WHEN2"}}},
            {"Resources": {"a": {"Tag": "x", "Name": "y"}}},
        ],
    )


# ---------------------------------------------------------------------------
# Round 5: per-origin inline calls ('pexpr' slots) — inline function
# calls in value scopes whose query arguments resolve per candidate
# origin. Precomputed once per (document, origin) on the host
# (fnvars._pexpr_scopes), encoded with the fn_origin column, and
# selected per origin label by the kernels (StepFnVar per_origin).
# Reference semantics: eval_context.rs:1483-1485 (ValueScope query
# re-rooting) + resolve_function in the clause's scope.
# ---------------------------------------------------------------------------

PER_ORIGIN_DOCS = [
    {"Resources": {
        "a": {"Name": "abc", "Limit": "10", "Size": 5,
              "Tags": ["x", "y"], "Type": "A"},
        "b": {"Name": "DEF", "Limit": "3", "Size": 5,
              "Tags": ["z"], "Type": "B"},
    }},
    {"Resources": {
        "a": {"Name": "xyz", "Limit": "100", "Size": 1,
              "Tags": [], "Type": "A"},
    }},
    {"Other": 1},
]


def test_per_origin_inline_call_in_block():
    """The canonical shape: `Resources.* { Name == to_lower(Name) }` —
    the argument query re-roots at each candidate, so the RHS differs
    per origin."""
    _differential(
        """
rule r when Resources exists {
    Resources.* { Name == to_lower(Name) }
}
""",
        PER_ORIGIN_DOCS,
    )


def test_per_origin_ordering_compare():
    """Ordering against a per-origin function result exercises the
    non-shared query-RHS ordering arm with per-origin labels."""
    _differential(
        """
rule r when Resources exists {
    Resources.* { Size < parse_int(Limit) }
}
""",
        PER_ORIGIN_DOCS,
    )


def test_per_origin_in_type_block():
    """Type-block sugar: origins are the type-filtered resources
    (eval_type_block_clause:1424)."""
    _differential(
        """
rule r when Resources exists {
    AWS::X::Y {
        Properties.Ref == to_upper(Properties.Base)
    }
}
""",
        [
            {"Resources": {"a": {
                "Type": "AWS::X::Y",
                "Properties": {"Ref": "ONE", "Base": "one"},
            }}},
            {"Resources": {"a": {
                "Type": "AWS::X::Y",
                "Properties": {"Ref": "one", "Base": "one"},
            }}},
            {"Resources": {"a": {"Type": "Other",
                                 "Properties": {"Ref": "x", "Base": "y"}}}},
        ],
    )


def test_per_origin_nested_blocks():
    """Origins compose through nested value scopes: the innermost
    candidate set is the composition of both block queries, and each
    result binds to its innermost origin."""
    _differential(
        """
rule r when Groups exists {
    Groups.* {
        Members.* { Id == to_lower(Id) }
    }
}
""",
        [
            {"Groups": {
                "g1": {"Members": {"m1": {"Id": "aa"}, "m2": {"Id": "BB"}}},
                "g2": {"Members": {"m3": {"Id": "cc"}}},
            }},
            {"Groups": {"g1": {"Members": {"m1": {"Id": "ok"}}}}},
            {"Other": 1},
        ],
    )


def test_per_origin_when_block_and_vs_let():
    """A when block inside the value scope adds its lets to the
    resolution scope; the call references a value-scope-bound variable
    (vars_ & vs_bound — the other way a call becomes origin-dependent)."""
    _differential(
        """
rule r when Resources exists {
    Resources.* {
        when Type == 'A' {
            let parts = Tags[*]
            Name == join(%parts, ',')
        }
    }
}
""",
        [
            {"Resources": {
                "a": {"Type": "A", "Name": "x,y", "Tags": ["x", "y"]},
                "b": {"Type": "A", "Name": "nope", "Tags": ["z"]},
            }},
            {"Resources": {"a": {"Type": "B", "Name": "n", "Tags": ["t"]}}},
            {"Resources": {"a": {"Type": "A", "Name": "z", "Tags": ["z"]}}},
        ],
    )


def test_per_origin_in_membership():
    """IN against a per-origin result set (json_parse produces a list
    per origin; membership joins per origin label)."""
    _differential(
        """
rule r when Resources exists {
    Resources.* { Name IN json_parse(Allowed) }
}
""",
        [
            {"Resources": {
                "a": {"Name": "x", "Allowed": '["x", "y"]'},
                "b": {"Name": "q", "Allowed": '["x", "y"]'},
            }},
            {"Resources": {"a": {"Name": "y", "Allowed": '["y"]'}}},
        ],
    )


def test_per_origin_mixed_with_shared_expr():
    """A root-safe inline call (shared slot) and a per-origin call in
    the same file keep distinct slot namespaces."""
    _differential(
        """
let names = Resources.*.Name
rule shared when Resources exists { 'abc,DEF' == join(%names, ',') }
rule perorigin when Resources exists {
    Resources.* { Name == to_upper(Name) }
}
""",
        [
            {"Resources": {"a": {"Name": "abc"}, "b": {"Name": "DEF"}}},
            {"Resources": {"a": {"Name": "ABC"}}},
        ],
    )


def test_per_origin_fn_error_doc_routes_to_oracle():
    """A document on which the per-origin precompute raises (parse_int
    on a non-numeric string) lands in the error set and must evaluate
    on the oracle — statuses via the backend stay identical."""
    from guard_tpu.ops.fnvars import precomputable_fn_vars

    rules = """
rule r when Resources exists {
    Resources.* { Size < parse_int(Limit) }
}
"""
    rf = parse_rules_file(rules, "fn.guard")
    docs = [
        from_plain({"Resources": {"a": {"Size": 1, "Limit": "10"}}}),
        from_plain({"Resources": {"a": {"Size": 1, "Limit": "oops"}}}),
    ]
    assert precomputable_fn_vars(rf)
    fn_vars, fn_vals, fn_err = precompute_fn_values(rf, docs)
    assert fn_err == {1}, "non-numeric Limit doc must flag a fn error"
    batch, interner = encode_batch(
        docs, fn_values=fn_vals, fn_var_order=fn_vars
    )
    compiled = compile_rules_file(rf, interner)
    assert not compiled.host_rules
    ev = BatchEvaluator(compiled)
    statuses = ev(batch)
    # doc 0 decides on device and must match the oracle
    assert STATUS[int(statuses[0, 0])] == _oracle(rf, docs[0])["r"]


def test_per_origin_inside_filter_lowers():
    """Round 5b: calls inside query FILTERS lower too — candidate
    sets replay from the recorded query prefix
    (fnvars._filter_candidates). Differential battery in
    test_per_origin_call_inside_filter below."""
    rules = """
rule r when Resources exists {
    Resources.*[ Name == to_lower(Name) ] exists
}
"""
    rf = parse_rules_file(rules, "fn.guard")
    batch, interner = encode_batch(
        [from_plain(PER_ORIGIN_DOCS[0])]
    )
    compiled = compile_rules_file(rf, interner)
    assert not compiled.host_rules


def test_per_origin_backend_cli_parity(tmp_path):
    """End-to-end: `validate --backend tpu` over per-origin rules is
    byte-identical to the CPU backend."""
    import json as _json
    import subprocess
    import sys

    rules = tmp_path / "r.guard"
    rules.write_text(
        "rule r when Resources exists {\n"
        "    Resources.* { Name == to_lower(Name) }\n"
        "}\n"
    )
    for i, doc in enumerate(PER_ORIGIN_DOCS):
        (tmp_path / f"d{i}.json").write_text(_json.dumps(doc))
    outs = {}
    for backend in ("cpu", "tpu"):
        args = [sys.executable, "-m", "guard_tpu.cli", "validate",
                "-r", str(rules), "-d", str(tmp_path),
                "--show-summary", "all"]
        if backend == "tpu":
            args += ["--backend", "tpu"]
        proc = subprocess.run(args, capture_output=True, text=True,
                              timeout=300)
        outs[backend] = (proc.returncode, proc.stdout)
    assert outs["cpu"] == outs["tpu"]


def test_per_origin_when_guard_protects_call():
    """The defensive-guard idiom: `when <guard> { fn(...) }` must NOT
    precompute the call for guard-false origins — a doc whose bad
    input is exactly what the guard excludes stays on the device path
    with no spurious fn error (review finding, round 5)."""
    rules = """
rule r when Resources exists {
    Resources.* {
        when Limit == /^[0-9]+$/ {
            Size < parse_int(Limit)
        }
    }
}
"""
    docs_plain = [
        {"Resources": {"a": {"Size": 1, "Limit": "10"}}},
        # guard-false origin: parse_int would raise, but the oracle
        # never evaluates it (when-gate SKIPs)
        {"Resources": {"a": {"Size": 1, "Limit": "oops"}}},
        {"Resources": {
            "a": {"Size": 9, "Limit": "5"},
            "b": {"Size": 1, "Limit": "not-a-number"},
        }},
    ]
    rf = parse_rules_file(rules, "fn.guard")
    docs = [from_plain(d) for d in docs_plain]
    fn_vars, fn_vals, fn_err = precompute_fn_values(rf, docs)
    assert not fn_err, (
        "guard-false origins must not flag fn errors — the when gate "
        "excludes them from precompute"
    )
    _differential(rules, docs_plain)


def test_per_origin_root_lhs_makes_no_slot():
    """A clause whose LHS re-roots at the document root (head variable
    bound on the root chain) cannot consume a per-origin RHS — no
    pexpr slot is created (nothing precomputes or encodes) and the
    rule falls back to the host."""
    from guard_tpu.ops.fnvars import fn_slots

    rules = """
let heads = Resources.*
rule r when Resources exists {
    Resources.* { %heads.Name == to_lower(Name) }
}
"""
    rf = parse_rules_file(rules, "fn.guard")
    layout = fn_slots(rf)
    assert not layout.pexpr_slots, "refused clause must not reserve a slot"
    docs = [from_plain({"Resources": {"a": {"Name": "abc"}}})]
    fn_vars, fn_vals, _ = precompute_fn_values(rf, docs)
    batch, interner = encode_batch(
        docs, fn_values=fn_vals, fn_var_order=fn_vars
    )
    compiled = compile_rules_file(rf, interner)
    assert [r.rule_name for r in compiled.host_rules] == ["r"]


def test_per_origin_slash_key_path_collision_routes_to_oracle():
    """Paths are unescaped slash-joined strings, so a map key
    containing '/' can collide with a nested path ('Resources' ->
    'x/Name' vs 'Resources' -> 'x' -> 'Name'). Such documents must
    flag num_exotic (oracle routing) rather than silently gating the
    per-origin RHS off the wrong node (review finding, round 5)."""
    rules = """
rule r when Resources exists {
    Resources.* { Name == to_lower(Name) }
}
"""
    rf = parse_rules_file(rules, "fn.guard")
    colliding = {"Resources": {"x/Name": {"Name": "ABC"}, "x": {"Name": "def"}}}
    clean = {"Resources": {"a": {"Name": "ABC"}}}
    docs = [from_plain(colliding), from_plain(clean)]
    fn_vars, fn_vals, fn_err = precompute_fn_values(rf, docs)
    assert not fn_err
    batch, interner = encode_batch(
        docs, fn_values=fn_vals, fn_var_order=fn_vars
    )
    assert bool(batch.num_exotic[0]), (
        "colliding path space must route the doc to the oracle"
    )
    assert not bool(batch.num_exotic[1])
    # the clean doc still decides on device and matches the oracle
    compiled = compile_rules_file(rf, interner)
    assert not compiled.host_rules
    statuses = BatchEvaluator(compiled)(batch)
    assert STATUS[int(statuses[1, 0])] == _oracle(rf, docs[1])["r"]
    # and the oracle's answer for the colliding doc is what users get
    assert _oracle(rf, docs[0])["r"] == "FAIL"


# ---------------------------------------------------------------------------
# Round 5b: cross-scope value-scope variables as clause RHS ('pvar'
# slots) and per-origin calls inside query filters — both ride the
# per-use-site candidate replay (fnvars._pexpr_scopes filter entries
# mirroring scopes._retrieve_filter).
# ---------------------------------------------------------------------------


def test_cross_scope_var_rhs_in_filter():
    """The canonical cross_scope_value_var shape: a block let used
    inside a filter one scope deeper (`Properties[ Kind == %t ]`)."""
    _differential(
        """
rule r when Resources exists {
    Resources.* {
        let t = Type
        Properties[ Kind == %t ] exists
    }
}
""",
        [
            {"Resources": {"a": {
                "Type": "A",
                "Properties": {"p1": {"Kind": "A"}, "p2": {"Kind": "B"}},
            }}},
            {"Resources": {"a": {
                "Type": "X", "Properties": {"p1": {"Kind": "A"}},
            }}},
            {"Resources": {
                "a": {"Type": "A", "Properties": {"p": {"Kind": "A"}}},
                "b": {"Type": "B", "Properties": {"p": {"Kind": "A"}}},
            }},
            {"Other": 1},
        ],
    )


def test_cross_scope_var_rhs_in_nested_block():
    """Use in a nested block (not a filter): each member compares
    against ITS group's id."""
    _differential(
        """
rule r when Groups exists {
    Groups.* {
        let gid = Id
        Members.* { Owner == %gid }
    }
}
""",
        [
            {"Groups": {
                "g1": {"Id": "g1x",
                       "Members": {"m1": {"Owner": "g1x"},
                                   "m2": {"Owner": "zz"}}},
                "g2": {"Id": "g2x", "Members": {"m": {"Owner": "g2x"}}},
            }},
            {"Groups": {"g1": {"Id": "q", "Members": {"m": {"Owner": "q"}}}}},
        ],
    )


def test_cross_scope_var_ops_and_shadowing():
    """Ordering and IN against cross-scope vars; an inner rebinding
    shadows the outer let for deeper uses."""
    _differential(
        """
rule caps when Resources exists {
    Resources.* {
        let cap = Cap
        Disks[ Size <= %cap ] !empty
    }
}
rule shadow when Resources exists {
    Resources.* {
        let t = Outer
        Props.* {
            let t = Inner
            Checks[ V == %t ] exists
        }
    }
}
""",
        [
            {"Resources": {"a": {
                "Cap": 10,
                "Disks": {"d1": {"Size": 5}, "d2": {"Size": 50}},
                "Outer": "o", "Props": {
                    "p": {"Inner": "i", "Checks": {"c": {"V": "i"}}},
                },
            }}},
            {"Resources": {"a": {
                "Cap": 1, "Disks": {"d": {"Size": 5}},
                "Outer": "o", "Props": {
                    "p": {"Inner": "i", "Checks": {"c": {"V": "o"}}},
                },
            }}},
        ],
    )


def test_cross_scope_var_literal_binding():
    """A literal let in a value scope used one scope deeper resolves
    to the literal for every origin."""
    _differential(
        """
rule r when Resources exists {
    Resources.* {
        let want = 'gold'
        Tags[ Tier == %want ] exists
    }
}
""",
        [
            {"Resources": {"a": {"Tags": {"t": {"Tier": "gold"}}}}},
            {"Resources": {"a": {"Tags": {"t": {"Tier": "iron"}}}}},
        ],
    )


def test_cross_scope_var_unresolved_routes_to_oracle():
    """An origin where the binding query does not resolve (missing
    Type) needs per-origin UnResolved accounting the kernels don't
    model — the doc routes to the oracle via the fn-error channel."""
    rules = """
rule r when Resources exists {
    Resources.* {
        let t = Type
        Properties[ Kind == %t ] exists
    }
}
"""
    rf = parse_rules_file(rules, "fn.guard")
    docs = [
        from_plain({"Resources": {"a": {
            "Type": "A", "Properties": {"p": {"Kind": "A"}},
        }}}),
        from_plain({"Resources": {"a": {
            "Properties": {"p": {"Kind": "A"}},  # no Type
        }}}),
    ]
    fn_vars, fn_vals, fn_err = precompute_fn_values(rf, docs)
    assert fn_err == {1}
    batch, interner = encode_batch(
        docs, fn_values=fn_vals, fn_var_order=fn_vars
    )
    compiled = compile_rules_file(rf, interner)
    assert not compiled.host_rules
    statuses = BatchEvaluator(compiled)(batch)
    assert STATUS[int(statuses[0, 0])] == _oracle(rf, docs[0])["r"]


def test_per_origin_call_inside_filter():
    """Per-origin inline calls inside query filters lower via the
    same candidate replay (formerly the last host-only fn shape)."""
    _differential(
        """
rule r when Resources exists {
    Resources.*[ Name == to_lower(Name) ] exists
}
rule deep when Resources exists {
    Resources.*.Tags[ Id == to_upper(Id) ] !empty
}
""",
        [
            {"Resources": {
                "a": {"Name": "abc", "Tags": {"t": {"Id": "XY"}}},
                "b": {"Name": "DEF", "Tags": {"t": {"Id": "zz"}}},
            }},
            {"Resources": {"a": {"Name": "ZZZ", "Tags": {"t": {"Id": "A"}}}}},
            {"Other": 1},
        ],
    )


def test_cross_scope_var_head_use_stays_host():
    """A HEAD use of a cross-scope variable starts a fresh traversal
    per origin — still host-only (cross_scope_value_var_head)."""
    rules = """
rule r when Resources exists {
    Resources.* {
        let t = Type
        Properties { %t exists }
    }
}
"""
    rf = parse_rules_file(rules, "fn.guard")
    batch, interner = encode_batch(
        [from_plain({"Resources": {"a": {"Type": "A", "Properties": {}}}})]
    )
    compiled = compile_rules_file(rf, interner)
    assert [r.rule_name for r in compiled.host_rules] == ["r"]


def test_cross_scope_excluded_indirection_stays_host():
    """A value-scope let that indirects to an excluded builtin via a
    SIBLING value-scope let must not precompute (review finding,
    round 5b): the name-level exclusion fixpoint covers every let in
    the file, not just root-basis ones."""
    from guard_tpu.ops.fnvars import fn_slots

    rules = """
rule r when Resources exists {
    Resources.* {
        let a = parse_char(Code)
        let t = %a
        Props[ K == %t ] exists
    }
}
"""
    rf = parse_rules_file(rules, "fn.guard")
    layout = fn_slots(rf)
    assert not layout.pvar_slots, "excluded indirection must not slot"
    docs = [from_plain({"Resources": {"x": {
        "Code": "k", "Props": {"p": {"K": "k"}},
    }}})]
    fn_vars, fn_vals, _ = precompute_fn_values(rf, docs)
    batch, interner = encode_batch(
        docs, fn_values=fn_vals, fn_var_order=fn_vars
    )
    compiled = compile_rules_file(rf, interner)
    assert [r.rule_name for r in compiled.host_rules] == ["r"]
