"""Drift guard for the documented host-only surface (VERDICT r3 item 7).

`guard_tpu.ops.ir.HOST_ONLY_CONSTRUCTS` is the module's own statement of
what refuses lowering. Round 3's verdict caught the docstring claiming
four constructs refused that had lowered rounds earlier; this suite
makes that class of drift impossible: every documented construct has a
canonical example here that must actually fall back to the host, the
key sets must match exactly, and the constructs the old docstring
wrongly named (function calls, query-to-query compares, map literals,
root-bound variable captures) must lower with zero host rules.
"""

import pytest

from guard_tpu.core.parser import parse_rules_file
from guard_tpu.core.values import from_plain
from guard_tpu.ops.encoder import encode_batch
from guard_tpu.ops.ir import HOST_ONLY_CONSTRUCTS, compile_rules_file

DOC = {
    "Resources": {
        "a": {
            "Type": "A",
            "Name": "n",
            "Tags": [{"Value": "x"}],
            "Properties": {"Enabled": True, "Kind": "A"},
        }
    }
}

# One canonical refusing example per documented construct. Keys must
# match HOST_ONLY_CONSTRUCTS exactly (asserted below).
REFUSING_EXAMPLES = {
    "now_builtin": """
let t = now()
rule r when Resources exists { %t > 0 }
""",
    "parse_char_builtin": """
let c = parse_char(Resources.*.Name)
rule r when Resources exists { %c exists }
""",
    "cross_scope_value_var_head": """
rule r when Resources exists {
    Resources.* {
        let t = Type
        Properties { %t exists }
    }
}
""",
    "variable_capture": """
rule r when Resources exists {
    Resources[ x | Type == 'A' ].Properties exists
    %x !empty
}
""",
}

# Constructs the stale round-2 docstring claimed refused; all lower.
LOWERING_EXAMPLES = {
    "function_call_let_and_inline": """
let upper = to_upper(Resources.*.Name)
rule r when Resources exists { %upper !empty }
""",
    "query_to_query_compare": """
rule r when Resources exists {
    Resources.a.Name == Resources.a.Type or
    Resources.a.Name exists
}
""",
    "map_literal_rhs": """
rule r when Resources exists {
    Resources.a.Properties == { Enabled: true, Kind: "A" }
}
""",
    "root_bound_variable_in_filter": """
let kinds = Resources.*.Type
rule r when Resources exists {
    Resources.*.Properties[ Kind IN %kinds ] exists
}
""",
    # round 5: the same function-let NAME bound in several when blocks
    # disambiguates by binding identity (fnvars keys slots on the
    # FunctionExpr object); differential coverage in
    # tests/test_fn_lowering.py::test_same_fn_let_in_two_when_blocks
    "fn_let_multi_when_block": """
rule r {
    when Resources exists {
        let u = to_upper(Resources.*.Name)
        %u !empty
    }
    when Outputs exists {
        let u = to_upper(Outputs.*.Name)
        %u !empty
    }
}
""",
    # round 5: origin-dependent inline calls in block value scopes
    # lower via per-origin precompute (fnvars 'pexpr' slots + the
    # fn_origin column); only the FILTER-nested form above still
    # refuses. Differential coverage in
    # tests/test_fn_lowering.py::test_per_origin_inline_call_in_block
    "per_origin_inline_call_in_block": """
rule r when Resources exists {
    Resources.* { Name == to_lower(Name) }
}
""",
    # round 5: a capture whose name is never referenced as %x is
    # unobservable (captures only surface through variable
    # resolution), so the marker lowers as the unnamed equivalent
    "unreferenced_variable_capture": """
rule r when Resources exists {
    Resources[ x | Type == 'A' ].Properties exists
}
""",
    # round 5: filter candidate sets replay from the recorded query
    # prefix, so per-origin inline calls inside filters lower too
    "per_origin_inline_call_in_filter": """
rule r when Resources exists {
    Resources.*[ Name == to_lower(Name) ] exists
}
""",
    # round 5: a value-scope variable used as a bare clause RHS in a
    # DEEPER scope precomputes per use-site candidate ('pvar' slots).
    # Differential coverage in
    # tests/test_fn_lowering.py::test_cross_scope_var_rhs_in_filter
    "cross_scope_value_var_rhs": """
rule r when Resources exists {
    Resources.* {
        let t = Type
        Properties[ Kind == %t ] exists
    }
}
""",
}


def _compile(text):
    rf = parse_rules_file(text, "refusals.guard")
    batch, interner = encode_batch([from_plain(DOC)])
    return compile_rules_file(rf, interner)


def test_documented_keys_have_examples_and_vice_versa():
    assert set(REFUSING_EXAMPLES) == set(HOST_ONLY_CONSTRUCTS), (
        "HOST_ONLY_CONSTRUCTS and the canonical examples drifted apart; "
        "update both together"
    )


@pytest.mark.parametrize("construct", sorted(REFUSING_EXAMPLES))
def test_documented_construct_actually_refuses(construct):
    compiled = _compile(REFUSING_EXAMPLES[construct])
    assert [r.rule_name for r in compiled.host_rules] == ["r"], (
        f"{construct} is documented host-only in ir.HOST_ONLY_CONSTRUCTS "
        "but lowered — remove it from the documented list"
    )


@pytest.mark.parametrize("construct", sorted(LOWERING_EXAMPLES))
def test_formerly_documented_constructs_lower(construct):
    compiled = _compile(LOWERING_EXAMPLES[construct])
    assert not compiled.host_rules, (
        f"{construct} regressed to host fallback: "
        f"{[r.rule_name for r in compiled.host_rules]}"
    )
    assert [r.name for r in compiled.rules] == ["r"]


def test_unreferenced_capture_statuses_match_oracle():
    """The marker-ignored lowering must be status-identical to the
    oracle (which still records the capture, unobservably)."""
    from guard_tpu.commands.report import rule_statuses_from_root
    from guard_tpu.core.evaluator import eval_rules_file
    from guard_tpu.core.scopes import RootScope
    from guard_tpu.ops.kernels import BatchEvaluator

    rules = """
rule r when Resources exists {
    Resources[ x | Type == 'A' ].Properties.Enabled == true
}
rule proj when Resources exists {
    Resources[ lid ].Type exists
}
"""
    docs_plain = [
        DOC,
        {"Resources": {"a": {"Type": "B", "Properties": {"Enabled": True}}}},
        {"Resources": {
            "a": {"Type": "A", "Properties": {"Enabled": False}},
            "b": {"Type": "A", "Properties": {"Enabled": True}},
        }},
    ]
    rf = parse_rules_file(rules, "cap.guard")
    docs = [from_plain(d) for d in docs_plain]
    batch, interner = encode_batch(docs)
    compiled = compile_rules_file(rf, interner)
    assert not compiled.host_rules, [r.rule_name for r in compiled.host_rules]
    statuses = BatchEvaluator(compiled)(batch)
    S = {0: "PASS", 1: "FAIL", 2: "SKIP"}
    for di, doc in enumerate(docs):
        scope = RootScope(rf, doc)
        eval_rules_file(rf, scope, None)
        oracle = {
            n: s.value
            for n, s in rule_statuses_from_root(
                scope.reset_recorder().extract()
            ).items()
        }
        for ri, crule in enumerate(compiled.rules):
            assert S[int(statuses[di, ri])] == oracle[crule.name], (
                di, crule.name,
            )
