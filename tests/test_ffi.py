"""C-ABI FFI layer: build the shim and drive it from a real C caller."""

import json
import pathlib
import subprocess

import pytest

NATIVE = pathlib.Path(__file__).resolve().parent.parent / "native"
REPO = NATIVE.parent


def _build() -> bool:
    if (NATIVE / "guard_ffi_test").exists():
        return True
    try:
        subprocess.run(
            ["sh", str(NATIVE / "build_ffi.sh")], check=True, capture_output=True
        )
    except (subprocess.CalledProcessError, OSError):
        return False
    return (NATIVE / "guard_ffi_test").exists()


pytestmark = pytest.mark.skipif(not _build(), reason="ffi build unavailable")


def test_ffi_run_checks_from_c():
    import os

    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO)
    out = subprocess.run(
        [str(NATIVE / "guard_ffi_test")],
        capture_output=True,
        text=True,
        env=env,
    )
    assert out.returncode == 0, out.stderr
    reports = json.loads(out.stdout)
    assert reports[0]["status"] == "FAIL"  # Resources is empty
    assert reports[0]["name"] == "data.json"
