"""Telemetry-plane suite (guard_tpu/utils/telemetry.py): span
nesting/attribute correctness, the disabled-mode zero-allocation path,
Chrome trace_event JSON well-formedness, the worker-span round-trip
through the spawn ingest pool, and parity — the `--trace-out` /
`--metrics-out` export flags must leave report bytes and exit codes
bit-identical across worker counts and pack modes. Observability may
cost microseconds, never output."""

import json
import pathlib
import pickle
import sys

import pytest

from guard_tpu.cli import run
from guard_tpu.parallel import ingest
from guard_tpu.utils import telemetry
from guard_tpu.utils.io import Reader, Writer

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent / "tools"))

from check_metrics_schema import check_snapshot  # noqa: E402

RULES = (
    "let b = Resources.*[ Type == 'AWS::S3::Bucket' ]\n"
    "rule sse when %b !empty { %b.Properties.Enc == true }\n"
)


@pytest.fixture(autouse=True)
def _clean_telemetry():
    """Every test starts and ends with tracing off, empty buffers and
    a fully zeroed registry (persistent histograms included)."""
    telemetry.disable()
    telemetry.reset_trace()
    telemetry.REGISTRY.reset(include_persistent=True)
    yield
    telemetry.disable()
    telemetry.reset_trace()
    telemetry.REGISTRY.reset(include_persistent=True)


def _mk_corpus(tmp_path, n=8, fail=(2,)):
    rules = tmp_path / "rules.guard"
    rules.write_text(RULES)
    data = tmp_path / "data"
    data.mkdir(exist_ok=True)
    for i in range(n):
        doc = {
            "Resources": {
                "b": {
                    "Type": "AWS::S3::Bucket",
                    "Properties": {"Enc": i not in fail},
                }
            }
        }
        (data / f"t{i:02d}.json").write_text(json.dumps(doc))
    return rules, data


# ------------------------------------------------------ span semantics


def test_span_nesting_links_parent_and_keeps_attrs():
    telemetry.enable()
    telemetry.reset_trace()
    with telemetry.span("dispatch", {"files": 3}):
        with telemetry.span("pack_compile"):
            pass
    # inner span finishes (and is recorded) first
    assert [r["name"] for r in telemetry._TRACE] == [
        "pack_compile", "dispatch",
    ]
    inner, outer = telemetry._TRACE
    assert inner["parent"] == outer["sid"]
    assert outer["parent"] == 0
    assert outer["attrs"] == {"files": 3}
    assert outer["lane"] == "dispatch"
    assert inner["lane"] == "rules"
    rolls = telemetry.REGISTRY.span_rollups()
    assert rolls["dispatch"]["count"] == 1
    assert rolls["pack_compile"]["count"] == 1
    # completed spans also feed the per-stage histogram
    assert telemetry.REGISTRY.histogram("stage.dispatch").count == 1


def test_span_ids_are_monotonic_and_deterministic():
    telemetry.enable()
    telemetry.reset_trace()
    for _ in range(5):
        with telemetry.span("report"):
            pass
    sids = [r["sid"] for r in telemetry._TRACE]
    assert sids == sorted(sids)
    assert len(set(sids)) == 5


def test_span_annotates_error_class_on_exception():
    telemetry.enable()
    telemetry.reset_trace()
    with pytest.raises(ValueError):
        with telemetry.span("oracle"):
            raise ValueError("boom")
    (rec,) = telemetry._TRACE
    assert rec["attrs"]["error_class"] == "ValueError"


def test_span_begin_end_records_like_with_block():
    telemetry.enable()
    telemetry.reset_trace()
    sp = telemetry.span_begin("serve_request")
    sp.set("error_class", "RequestTimeout")
    telemetry.span_end(sp)
    (rec,) = telemetry._TRACE
    assert rec["name"] == "serve_request"
    assert rec["lane"] == "serve"
    assert rec["attrs"]["error_class"] == "RequestTimeout"


# -------------------------------------------------- disabled-mode cost


def test_disabled_span_is_the_shared_noop_singleton():
    sp = telemetry.span("dispatch", {"files": 3})
    # no allocation: every disabled span() IS the same object
    assert sp is telemetry.span("encode")
    assert sp is telemetry._NOOP
    assert telemetry.span_begin("rim_reduce") is telemetry._NOOP
    with sp:
        sp.set("key", "value")
    telemetry.span_end(telemetry.span_begin("report"))
    telemetry.event("fault.retries")
    assert telemetry._TRACE == []
    assert telemetry._EVENTS == []
    assert telemetry.REGISTRY.span_rollups() == {}


def test_evented_counters_emit_instant_events_only_when_on():
    c = telemetry.EventedCounters("fault", {"retries": 0})
    c["retries"] += 1  # tracing off: plain dict semantics
    assert telemetry._EVENTS == []
    telemetry.enable()
    telemetry.reset_trace()
    c["retries"] += 1
    assert [e["name"] for e in telemetry._EVENTS] == ["fault.retries"]
    c["retries"] = 0  # resets/decreases never produce events
    assert len(telemetry._EVENTS) == 1


# ---------------------------------------------- registry + histograms


def test_histogram_buckets_sum_and_quantiles_order():
    h = telemetry.Histogram("t")
    for v in (0.001, 0.002, 0.004, 1.5):
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == 4
    assert sum(snap["buckets"].values()) == 4
    assert snap["min_seconds"] == 0.001
    assert snap["max_seconds"] == 1.5
    assert snap["p50_seconds"] is not None
    assert snap["p50_seconds"] <= snap["p99_seconds"]
    # non-positive durations land in the underflow bucket, not a crash
    h.observe(0.0)
    assert h.counts[0] == 1


def test_persistent_histogram_survives_plain_reset():
    h = telemetry.REGISTRY.histogram("serve_request_seconds",
                                     persistent=True)
    h.observe(0.1)
    telemetry.REGISTRY.reset()
    assert telemetry.REGISTRY.histogram("serve_request_seconds").count == 1
    telemetry.REGISTRY.reset(include_persistent=True)
    assert telemetry.REGISTRY.histogram("serve_request_seconds").count == 0


def test_reset_all_stats_clears_every_plane_at_once():
    from guard_tpu.ops import backend
    from guard_tpu.utils import faults

    telemetry.enable()
    telemetry.reset_trace()
    with telemetry.span("dispatch"):
        pass
    faults.FAULT_COUNTERS["retries"] += 1
    backend.RIM_COUNTERS["docs_materialized"] += 7
    telemetry.REGISTRY.set_gauge("g", 1.0)
    backend.reset_all_stats()
    assert faults.FAULT_COUNTERS["retries"] == 0
    assert backend.RIM_COUNTERS["docs_materialized"] == 0
    assert telemetry.REGISTRY.span_rollups() == {}
    assert telemetry.REGISTRY.snapshot()["gauges"] == {}
    # the trace buffer is an artifact log, not a stat: it survives
    assert len(telemetry._TRACE) == 1


def test_metrics_snapshot_passes_schema_checker():
    from guard_tpu.cache import results  # registers the result_cache group
    from guard_tpu.utils import faults  # registers the fault group

    telemetry.enable()
    telemetry.reset_trace()
    faults.FAULT_COUNTERS["retries"] += 1
    results.RESULT_COUNTERS["hits"] += 1
    with telemetry.span("rim_reduce"):
        pass
    snap = telemetry.metrics_snapshot()
    assert check_snapshot(
        snap, require_groups=("fault", "result_cache")
    ) == []
    # and the checker actually bites: a doctored histogram count fails
    snap["histograms"]["stage.rim_reduce"]["count"] += 1
    assert check_snapshot(snap)
    results.reset_result_cache_stats()


def test_result_cache_group_in_snapshot_contract():
    """v4: the incremental plane's counter group is part of the
    published snapshot shape — EXPECTED_GROUPS names it and a snapshot
    missing it fails the gate when required."""
    import tools.check_metrics_schema as cms
    from guard_tpu.cache import results  # noqa: F401 — registers group

    assert "result_cache" in cms.EXPECTED_GROUPS
    assert cms.KNOWN_SCHEMA_VERSION == telemetry.SCHEMA_VERSION
    snap = telemetry.metrics_snapshot()
    assert "result_cache" in snap["counters"]
    for key in ("hits", "misses", "stores", "corrupt_entries",
                "bytes_loaded", "bytes_stored"):
        assert key in snap["counters"]["result_cache"]
    doctored = json.loads(json.dumps(snap))
    del doctored["counters"]["result_cache"]
    assert check_snapshot(doctored, require_groups=("result_cache",))


def test_analysis_group_in_snapshot_contract():
    """v5: the static-analysis plane's counter group joined the
    published snapshot shape, alongside the plan_cache corrupt-cause
    split."""
    import tools.check_metrics_schema as cms
    from guard_tpu import analysis  # noqa: F401 — registers group
    from guard_tpu.ops import plan as plan_mod

    assert "analysis" in cms.EXPECTED_GROUPS
    assert cms.KNOWN_SCHEMA_VERSION == telemetry.SCHEMA_VERSION >= 5
    snap = telemetry.metrics_snapshot()
    assert "analysis" in snap["counters"]
    for key in ("invariants_checked", "violations", "lint_findings",
                "signatures_extracted"):
        assert key in snap["counters"]["analysis"]
    for key in ("corrupt_unreadable", "corrupt_version_mismatch",
                "corrupt_verify"):
        assert key in snap["counters"]["plan_cache"]
    assert plan_mod.plan_stats().keys() >= {"corrupt_verify"}
    doctored = json.loads(json.dumps(snap))
    del doctored["counters"]["analysis"]
    assert check_snapshot(doctored, require_groups=("analysis",))


def test_admission_group_in_snapshot_contract():
    """v6: the serving front door's counter group joined the published
    snapshot shape — quota admissions/rejections, breaker
    trips/probes/closes, sheds, transport bounds, follow stream."""
    import tools.check_metrics_schema as cms

    assert "admission" in cms.EXPECTED_GROUPS
    assert cms.KNOWN_SCHEMA_VERSION == telemetry.SCHEMA_VERSION >= 6
    snap = telemetry.metrics_snapshot()
    assert "admission" in snap["counters"]
    for key in ("admitted", "rejected_rate", "rejected_inflight",
                "rejected_queue_full", "rejected_body_size",
                "shed_solo", "breaker_trips", "breaker_probes",
                "breaker_closes", "follow_docs", "follow_batches"):
        assert key in snap["counters"]["admission"]
    doctored = json.loads(json.dumps(snap))
    del doctored["counters"]["admission"]
    assert check_snapshot(doctored, require_groups=("admission",))


def test_resume_and_gc_groups_in_snapshot_contract():
    """v7: the durability plane's counter groups joined the published
    snapshot shape — journal checkpoints/replays, drain sessions, and
    store-hygiene eviction stats. Both register with utils.telemetry
    itself, so every snapshot carries them."""
    import tools.check_metrics_schema as cms

    assert "resume" in cms.EXPECTED_GROUPS
    assert "gc" in cms.EXPECTED_GROUPS
    assert cms.KNOWN_SCHEMA_VERSION == telemetry.SCHEMA_VERSION == 7
    snap = telemetry.metrics_snapshot()
    assert "resume" in snap["counters"]
    for key in ("chunks_journaled", "chunks_replayed", "runs_resumed",
                "stale_cold_starts", "torn_records_dropped",
                "journal_degraded", "drained_sessions"):
        assert key in snap["counters"]["resume"]
    assert "gc" in snap["counters"]
    for key in ("runs", "files_evicted", "bytes_evicted",
                "orphan_tmps_reaped", "evict_errors"):
        assert key in snap["counters"]["gc"]
    doctored = json.loads(json.dumps(snap))
    del doctored["counters"]["resume"]
    assert check_snapshot(doctored, require_groups=("resume",))


def test_verify_and_lint_spans_roll_up():
    from guard_tpu.analysis.lint import lint_files
    from guard_tpu.analysis.verify import verify_plan
    from guard_tpu.commands.validate import RuleFile
    from guard_tpu.core.parser import parse_rules_file
    from guard_tpu.ops import plan as plan_mod

    telemetry.enable()
    rf = RuleFile(name="r.guard", full_name="r.guard", content=RULES,
                  rules=parse_rules_file(RULES, "r.guard"))
    plan = plan_mod.build_plan([rf])
    assert verify_plan(plan) == []
    lint_files([("r.guard", rf.rules)])
    rollups = telemetry.REGISTRY.span_rollups()
    assert rollups["verify_plan"]["count"] == 1
    assert rollups["lint"]["count"] == 1


def test_disabled_analysis_costs_one_branch(monkeypatch):
    """GUARD_TPU_ANALYSIS=0 (or verify=False) must short-circuit
    before any structure walk: verify hooks reduce to the enablement
    check, never touching the violation machinery."""
    from guard_tpu import analysis
    from guard_tpu.ops import plan as plan_mod

    monkeypatch.setenv("GUARD_TPU_ANALYSIS", "0")
    assert analysis.analysis_enabled(True) is False
    assert analysis.analysis_enabled(False) is False
    calls = []
    monkeypatch.setattr(
        "guard_tpu.analysis.verify.verify_plan",
        lambda plan: calls.append(plan) or [],
    )
    assert plan_mod._verify_enabled(True) is False
    assert calls == []  # the walk never ran
    monkeypatch.delenv("GUARD_TPU_ANALYSIS")
    assert plan_mod._verify_enabled(True) is True
    assert plan_mod._verify_enabled(False) is False  # flag alone gates too
    # spans stay the shared no-op singleton while tracing is off
    s1 = telemetry.span("verify_plan")
    s2 = telemetry.span("lint")
    assert s1 is s2


# -------------------------------------------------- trace export face


def test_trace_event_json_is_well_formed(tmp_path):
    telemetry.enable()
    telemetry.reset_trace()
    with telemetry.span("rule_parse", {"files": 1}):
        pass
    with telemetry.span("dispatch"):
        with telemetry.span("pack_compile"):
            pass
    telemetry.event("fault.retries", {"value": 1})
    path = tmp_path / "trace.json"
    telemetry.write_trace(str(path))
    doc = json.loads(path.read_text())
    assert doc["otherData"]["schema_version"] == telemetry.SCHEMA_VERSION
    evs = doc["traceEvents"]
    xs = [e for e in evs if e["ph"] == "X"]
    assert len(xs) == 3
    for e in xs:
        assert e["pid"] == 1
        assert e["ts"] >= 0 and e["dur"] >= 0
        assert "sid" in e["args"]
    # ts monotonic non-decreasing within every lane
    by_tid = {}
    for e in xs:
        by_tid.setdefault(e["tid"], []).append(e["ts"])
    for ts_list in by_tid.values():
        assert ts_list == sorted(ts_list)
    # instant events carry the global scope marker
    (inst,) = [e for e in evs if e["ph"] == "i"]
    assert inst["s"] == "g"
    assert inst["name"] == "fault.retries"
    # every used tid has thread_name metadata
    named = {e["tid"] for e in evs if e.get("name") == "thread_name"}
    used = {e["tid"] for e in evs if e["ph"] in ("X", "i")}
    assert used <= named
    # nesting is preserved through export: the child names its parent
    child = next(e for e in xs if e["name"] == "pack_compile")
    parent = next(e for e in xs if e["name"] == "dispatch")
    assert child["args"]["parent"] == parent["args"]["sid"]


# ------------------------------------------- worker span round-trips


def test_worker_span_records_survive_pickle_and_reanchor():
    recs = telemetry.worker_spans([
        ("read_parse", 122.5, 0.4),
        ("encode", 122.9, 0.5),
    ])
    recs = pickle.loads(pickle.dumps(recs))  # the pool boundary
    telemetry.enable()
    telemetry.reset_trace()
    telemetry.ingest_worker_spans(recs, chunk=3)
    assert len(telemetry._TRACE) == 2
    lanes = {r["lane"] for r in telemetry._TRACE}
    assert len(lanes) == 1 and next(iter(lanes)).startswith("worker-")
    assert all(r["attrs"]["chunk"] == 3 for r in telemetry._TRACE)
    rolls = telemetry.REGISTRY.span_rollups()
    assert rolls["read_parse"]["count"] == 1
    assert rolls["encode"]["count"] == 1
    # dropped without tracing (parent-side single branch)
    telemetry.disable()
    telemetry.ingest_worker_spans(recs, chunk=4)
    assert len(telemetry._TRACE) == 2


def test_worker_spans_round_trip_through_spawn_pool(tmp_path):
    ingest.close_shared_pools()
    try:
        rules, data = _mk_corpus(tmp_path, n=48, fail=())
        trace = tmp_path / "trace.json"
        w = Writer.buffered()
        rc = run(
            ["sweep", "-r", str(rules), "-d", str(data),
             "-M", str(tmp_path / "m.jsonl"), "-c", "8",
             "--backend", "tpu", "--ingest-workers", "2",
             "--trace-out", str(trace)],
            writer=w, reader=Reader(),
        )
        assert rc == 0
        if "worker pool unavailable" in w.err.getvalue():
            pytest.skip("spawn pool unavailable in this environment")
        doc = json.loads(trace.read_text())
        evs = doc["traceEvents"]
        lane_of = {
            e["tid"]: e["args"]["name"]
            for e in evs if e.get("name") == "thread_name"
        }
        wspans = [
            e for e in evs
            if e["ph"] == "X"
            and lane_of.get(e["tid"], "").startswith("worker-")
        ]
        assert wspans, "no worker-lane spans made it back to the trace"
        assert {"read_parse", "encode"} <= {e["name"] for e in wspans}
        assert all(e["args"].get("worker") for e in wspans)
    finally:
        ingest.close_shared_pools()


# ------------------------------------------------------- parity gates


def _validate(rules, data, *extra):
    w = Writer.buffered()
    rc = run(
        ["validate", "-r", str(rules), "-d", str(data),
         "--backend", "tpu", *extra],
        writer=w, reader=Reader(),
    )
    return rc, w.out.getvalue()


@pytest.mark.parametrize("workers", [0, 2])
@pytest.mark.parametrize("pack", [(), ("--no-pack",)])
def test_export_flags_leave_report_bytes_identical(tmp_path, workers,
                                                   pack):
    ingest.close_shared_pools()
    try:
        rules, data = _mk_corpus(tmp_path, n=8, fail=(2, 5))
        common = ("--ingest-workers", str(workers), *pack)
        base_rc, base_out = _validate(rules, data, *common)
        trace = tmp_path / "trace.json"
        metrics = tmp_path / "metrics.json"
        rc, out = _validate(
            rules, data, *common,
            "--trace-out", str(trace), "--metrics-out", str(metrics),
        )
        assert (rc, out) == (base_rc, base_out)
        # the exports themselves are well-formed
        json.loads(trace.read_text())
        snap = json.loads(metrics.read_text())
        assert snap["schema_version"] == telemetry.SCHEMA_VERSION
        assert check_snapshot(snap) == []
        # and tracing was switched back off by the CLI exit path
        assert not telemetry.enabled()
    finally:
        ingest.close_shared_pools()
