"""CLI integration tests mirroring the reference harness
(`/root/reference/guard/tests/utils.rs:9-56`): build the command from
argv, inject buffered Reader/Writer, assert the exit-code protocol
(validate 0/19/5, test 0/7/1) and key output fragments."""

import json
import pathlib

import pytest

from guard_tpu.cli import run
from guard_tpu.utils.io import Reader, Writer

RES = pathlib.Path("/root/reference/guard/resources")
EX = pathlib.Path("/root/reference/guard-examples")


def run_cli(args, stdin=""):
    w = Writer.buffered()
    code = run(args, writer=w, reader=Reader.from_string(stdin))
    return code, w.stripped(), w.err_to_stripped()


def test_validate_pass_exit_0():
    # Default --show-summary=fail on a fully compliant file prints nothing
    # (reference SummaryTable + CfnAware both stay silent on PASS).
    code, out, _ = run_cli(
        [
            "validate",
            "-r", str(RES / "validate" / "rules-dir" / "s3_bucket_public_read_prohibited.guard"),
            "-d", str(RES / "validate" / "data-dir" / "s3-public-read-prohibited-template-compliant.yaml"),
        ]
    )
    assert code == 0
    assert out == ""

    code, out, _ = run_cli(
        [
            "validate", "-S", "all",
            "-r", str(RES / "validate" / "rules-dir" / "s3_bucket_public_read_prohibited.guard"),
            "-d", str(RES / "validate" / "data-dir" / "s3-public-read-prohibited-template-compliant.yaml"),
        ]
    )
    assert code == 0
    assert "Status = PASS" in out
    assert "PASS rules" in out


def test_validate_fail_exit_19():
    code, out, _ = run_cli(
        [
            "validate",
            "-r", str(RES / "validate" / "rules-dir" / "s3_bucket_public_read_prohibited.guard"),
            "-d", str(RES / "validate" / "data-dir" / "s3-public-read-prohibited-template-non-compliant.yaml"),
        ]
    )
    assert code == 19
    assert "Status = FAIL" in out


def test_validate_undefined_variable_exit_5():
    # malformed-rule.guard references an undefined variable: the
    # reference errors at evaluation time (validate.rs:187 expects
    # INTERNAL_FAILURE = 5)
    code, _out, err = run_cli(
        [
            "validate",
            "-r", str(RES / "validate" / "malformed-rule.guard"),
            "-d", str(RES / "validate" / "data-dir" / "s3-public-read-prohibited-template-compliant.yaml"),
        ]
    )
    assert code == 5
    assert "Could not resolve variable" in err


def test_validate_invalid_rule_parse_error_exit_5():
    code, _out, err = run_cli(
        [
            "validate",
            "-r", str(RES / "test-command" / "rule-dir" / "invalid_rule.guard"),
            "-d", str(RES / "validate" / "data-dir" / "s3-public-read-prohibited-template-compliant.yaml"),
        ]
    )
    assert code == 5
    assert "Parse Error" in err


def test_validate_structured_json():
    code, out, _ = run_cli(
        [
            "validate", "--structured", "-o", "json", "-S", "none",
            "-r", str(RES / "validate" / "rules-dir" / "s3_bucket_server_side_encryption_enabled.guard"),
            "-d", str(RES / "validate" / "data-dir" / "s3-server-side-encryption-template-compliant.yaml"),
        ]
    )
    assert code == 0
    reports = json.loads(out)
    assert isinstance(reports, list) and reports[0]["status"] == "PASS"
    assert set(reports[0]) >= {"name", "status", "not_compliant", "compliant", "not_applicable"}


def test_validate_sarif_output():
    code, out, _ = run_cli(
        [
            "validate", "--structured", "-o", "sarif", "-S", "none",
            "-r", str(RES / "validate" / "rules-dir" / "s3_bucket_server_side_encryption_enabled.guard"),
            "-d", str(RES / "validate" / "data-dir" / "s3-server-side-encryption-template-non-compliant.yaml"),
        ]
    )
    assert code == 19
    sarif = json.loads(out)
    assert sarif["version"] == "2.1.0"
    assert sarif["runs"][0]["results"]


def test_validate_junit_output():
    code, out, _ = run_cli(
        [
            "validate", "--structured", "-o", "junit", "-S", "none",
            "-r", str(RES / "validate" / "rules-dir" / "s3_bucket_server_side_encryption_enabled.guard"),
            "-d", str(RES / "validate" / "data-dir" / "s3-server-side-encryption-template-compliant.yaml"),
        ]
    )
    assert code == 0
    assert out.startswith('<?xml version="1.0"')
    assert "<testsuites" in out


def test_validate_payload_mode():
    payload = json.dumps(
        {
            "rules": ["Resources !empty"],
            "data": ['{"Resources": {"a": {"T": 1}}}', '{"Resources": {}}'],
        }
    )
    code, out, _ = run_cli(["validate", "--payload", "-S", "all"], stdin=payload)
    assert code == 19  # second doc fails
    assert "DATA_STDIN[1] Status = PASS" in out
    assert "DATA_STDIN[2] Status = FAIL" in out


def test_validate_conflicting_flags():
    code, _out, err = run_cli(
        ["validate", "--structured", "-o", "single-line-summary",
         "-r", "x.guard"]
    )
    assert code == 5


def test_test_command_exit_codes():
    code, out, _ = run_cli(
        [
            "test",
            "-r", str(RES / "test-command" / "dir" / "s3_bucket_server_side_encryption_enabled.guard"),
            "-t", str(RES / "test-command" / "data-dir" / "s3_bucket_server_side_encryption_enabled.yaml"),
        ]
    )
    assert code == 0
    golden = (RES / "test-command" / "output-dir" / "test_data_file.out").read_text()
    assert out == golden

    code2, out2, _ = run_cli(
        [
            "test",
            "-r", str(RES / "test-command" / "dir" / "s3_bucket_server_side_encryption_enabled.guard"),
            "-t", str(RES / "test-command" / "data-dir" / "failing_test.yaml"),
        ]
    )
    assert code2 == 7
    assert "FAIL Rules:" in out2


def test_test_directory_mode():
    code, out, _ = run_cli(["test", "-d", str(RES / "test-command" / "dir")])
    assert code == 0
    assert "Testing Guard File" in out


def test_parse_tree_all_example_rules():
    for guard in sorted(EX.rglob("*.guard")):
        code, out, err = run_cli(["parse-tree", "-r", str(guard), "--print-json"])
        assert code == 0, f"{guard}: {err}"
        tree = json.loads(out)
        assert "guard_rules" in tree


def test_rulegen_self_check():
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".yaml", delete=False) as f:
        f.write(
            "Resources:\n  V:\n    Type: AWS::EC2::Volume\n"
            "    Properties:\n      Size: 100\n      Encrypted: true\n"
        )
        name = f.name
    code, out, _ = run_cli(["rulegen", "-t", name])
    assert code == 0
    assert "let aws_ec2_volume_resources" in out
    # generated rules must themselves parse (self-check)
    from guard_tpu.core.parser import parse_rules_file

    assert parse_rules_file(out, "") is not None


def test_completions():
    for shell in ("bash", "zsh", "fish"):
        code, out, _ = run_cli(["completions", "-s", shell])
        assert code == 0 and "validate" in out


def test_run_checks_api():
    import guard_tpu

    out = guard_tpu.run_checks(
        '{"Resources": {"b": {"Type": "T"}}}', "Resources !empty"
    )
    assert json.loads(out)[0]["status"] == "PASS"
    verbose = guard_tpu.run_checks("{}", "Resources !empty", verbose=True)
    # serde encoding: externally-tagged RecordType (functional.rs golden)
    assert "FileCheck" in json.loads(verbose)["container"]


def test_builders():
    from guard_tpu import TestBuilder, ValidateBuilder

    code, out, _err = (
        ValidateBuilder()
        .payload()
        .structured()
        .show_summary(["none"])
        .output_format("json")
        .try_build_and_execute(
            json.dumps({"rules": ["Resources !empty"], "data": ["{}"]})
        )
    )
    assert code == 19
    assert json.loads(out)[0]["status"] == "FAIL"


def test_lambda_handler():
    from guard_tpu.lambda_handler import handler

    out = handler(
        {
            "data": '{"Resources": {"x": {"T": 1}}}',
            "rules": ["Resources !empty", "Resources empty"],
            "verbose": False,
        }
    )
    statuses = [r[0]["status"] for r in out["message"]]
    assert statuses == ["PASS", "FAIL"]


def test_traversal_index():
    from guard_tpu.core.loader import load_document
    from guard_tpu.core.traversal import Traversal

    doc = load_document("Resources:\n  b:\n    Type: T\n")
    t = Traversal(doc)
    node = t.at("/Resources/b/Type")
    assert node is not None and node.value.val == "T"
    up = t.at("1#", node)
    assert up.value.self_path().s == "/Resources/b"


def test_completions_track_argparse_surface():
    """Completions are generated from the argparse parser, so every
    subcommand and long flag in the real CLI must appear in the bash
    script (VERDICT round 1: hand-maintained lists drift)."""
    import argparse

    from guard_tpu.cli import build_parser
    from guard_tpu.commands.completions import cli_surface, subcommands

    parser = build_parser()
    sub = next(
        a for a in parser._actions if isinstance(a, argparse._SubParsersAction)
    )
    surface = cli_surface()
    assert set(surface) == set(sub.choices)
    assert "sweep" in surface  # previously missing from the static lists
    for name, sp in sub.choices.items():
        expected = {
            o
            for a in sp._actions
            for o in a.option_strings
            if o.startswith("--")
        }
        assert set(surface[name]) == expected, name

    code, out, _ = run_cli(["completions", "-s", "bash"])
    assert code == 0
    for name in subcommands(surface):
        assert name in out
    for flags in surface.values():
        for f in flags:
            assert f in out


def test_missing_file_paths_exit_5_cleanly():
    for args in (
        ["parse-tree", "-r", "/nonexistent/file.guard"],
        ["rulegen", "-t", "/nonexistent/template.yaml"],
        ["test", "-r", "/nonexistent/r.guard", "-t", "/nonexistent/t.yaml"],
    ):
        code, _out, err = run_cli(args)
        assert code in (1, 5), args  # test command uses its own error code
        assert "nonexistent" in err and "Traceback" not in err, args
