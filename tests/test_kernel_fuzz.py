"""Seeded randomized differential fuzz of the DEVICE lowering: random
rule files over random documents, every (doc, rule) status compared
between the compiled kernels and the CPU oracle. This explores
interactions the fixed matrices cannot (filters over function vars,
orderings against query RHS inside when gates, interpolation chained
with membership, ...). Deterministic seeds keep CI stable; bump TRIALS
locally for deeper soaks."""

import random

import pytest

from guard_tpu.core.errors import GuardError
from guard_tpu.core.parser import parse_rules_file
from guard_tpu.core.scopes import RootScope
from guard_tpu.core.evaluator import eval_rules_file
from guard_tpu.core.values import from_plain
from guard_tpu.ops.encoder import encode_batch
from guard_tpu.ops.fnvars import precompute_fn_values
from guard_tpu.ops.ir import compile_rules_file
from guard_tpu.ops.kernels import BatchEvaluator

STATUS = {0: "PASS", 1: "FAIL", 2: "SKIP"}

KEYS = ["Type", "Name", "Size", "Enc", "Tags", "Props", "Env", "Arn", "Vals"]
TYPES = ["Bucket", "Volume", "Task", "Other"]
STRS = ["prod", "dev", "a", "arn:aws:s3", "PROD-1", ""]
NUMS = [0, 1, 7, 443, 16777217, -3]


def _rand_value(rng, depth=0):
    r = rng.random()
    if depth < 2 and r < 0.25:
        return {
            rng.choice(KEYS): _rand_value(rng, depth + 1)
            for _ in range(rng.randint(1, 3))
        }
    if depth < 2 and r < 0.4:
        return [_rand_value(rng, depth + 1) for _ in range(rng.randint(0, 3))]
    r = rng.random()
    if r < 0.35:
        return rng.choice(STRS)
    if r < 0.6:
        return rng.choice(NUMS)
    if r < 0.7:
        return rng.random() * 100
    if r < 0.8:
        return rng.choice([True, False])
    if r < 0.9:
        return None
    return rng.choice(STRS)


def _rand_doc(rng):
    resources = {}
    for i in range(rng.randint(1, 4)):
        res = {"Type": rng.choice(TYPES)}
        for _ in range(rng.randint(1, 4)):
            res[rng.choice(KEYS)] = _rand_value(rng)
        resources[f"r{i}"] = res
    doc = {"Resources": resources}
    if rng.random() < 0.4:
        doc["Settings"] = {"Allowed": rng.sample(STRS, 2), "Cap": rng.choice(NUMS)}
    return doc


def _lit(rng):
    r = rng.random()
    if r < 0.3:
        return f"'{rng.choice(STRS)}'"
    if r < 0.5:
        return str(rng.choice(NUMS))
    if r < 0.6:
        return rng.choice(["true", "false", "null", "1.5"])
    if r < 0.7:
        return rng.choice(["/prod/", "/^arn:/", "/\\d+/"])
    if r < 0.8:
        return rng.choice(["r(0,100)", "r[1,443]"])
    return rng.choice(["['prod', 'dev']", "[0, 1, 443]", "[]"])


def _op(rng):
    return rng.choice(["==", "!=", ">", ">=", "<", "<=", "in", "not in"])


def _unary(rng):
    return rng.choice(
        ["exists", "!exists", "empty", "!empty", "is_string", "is_list", "is_int"]
    )


def _clause(rng, i):
    key = rng.choice(KEYS)
    key2 = rng.choice(KEYS)
    some = rng.choice(["", "some "])
    shapes = [
        lambda: f"{some}Resources.*.{key} {_op(rng)} {_lit(rng)}",
        lambda: f"{some}Resources.*.{key} {_unary(rng)}",
        lambda: f"{some}Resources.*[ Type == '{rng.choice(TYPES)}' ].{key} {_op(rng)} {_lit(rng)}",
        lambda: f"{some}Resources.*.{key}.{key2} {_op(rng)} {_lit(rng)}",
        lambda: f"{some}Resources.*.{key} {_op(rng)} Resources.*.{key2}",
        lambda: f"{some}Resources.*[ {key} {_unary(rng)} ].{key2}[*] {_op(rng)} {_lit(rng)}",
        lambda: f"Resources[ keys == /r\\d/ ].{key} {_unary(rng)}",
        lambda: f"Resources[ keys {rng.choice(['in', 'not in', '!='])} {rng.choice(['/r1/', chr(39) + 'r0' + chr(39)])} ].{key} {_unary(rng)}",
        lambda: f"{some}Resources.*.{key}[0] {_op(rng)} {_lit(rng)}",
        lambda: f"Resources.*.{key} {{ this {_op(rng)} {_lit(rng)} }}",
        lambda: f"{some}Resources.*.Tags[*].{key} {_op(rng)} {_lit(rng)}",
    ]
    return rng.choice(shapes)()


def _rand_rules(rng, ti):
    parts = []
    nv = rng.randint(0, 2)
    var_names = []
    for v in range(nv):
        kind = rng.random()
        key = rng.choice(KEYS)
        if kind < 0.4:
            parts.append(
                f"let v{v} = Resources.*[ Type == '{rng.choice(TYPES)}' ]"
            )
        elif kind < 0.6:
            parts.append(f"let v{v} = some Resources.*.{key}")
        elif kind < 0.75:
            parts.append(f"let v{v} = count(Resources.*.{key})")
        elif kind < 0.9:
            parts.append(f"let v{v} = to_upper(Resources.*.Name)")
        else:
            parts.append(f"let v{v} = parse_int(Resources.*.Size)")
        var_names.append((f"v{v}", kind))
    for ri in range(rng.randint(2, 4)):
        gate = ""
        if rng.random() < 0.5:
            if var_names and rng.random() < 0.5:
                vn, kind = rng.choice(var_names)
                if kind < 0.6:
                    gate = f" when %{vn} !empty"
                elif kind < 0.75:
                    gate = f" when %{vn} {rng.choice(['==', '>', '<='])} {rng.choice(NUMS)}"
                else:
                    gate = f" when %{vn} !empty"
            else:
                gate = " when Resources exists"
        body = []
        for ci in range(rng.randint(1, 3)):
            if var_names and rng.random() < 0.35:
                vn, kind = rng.choice(var_names)
                if kind < 0.4:  # resource-set var
                    body.append(
                        rng.choice(
                            [
                                f"%{vn}.{rng.choice(KEYS)} {_op(rng)} {_lit(rng)}",
                                f"%{vn}[ {rng.choice(KEYS)} exists ].{rng.choice(KEYS)} {_unary(rng)}",
                                f"%{vn} {_unary(rng)}",
                            ]
                        )
                    )
                elif kind < 0.6:  # string-set var (some Resources.*.key)
                    body.append(
                        rng.choice(
                            [
                                f"%{vn} {_op(rng)} {rng.choice(NUMS)}",
                                f"Resources.%{vn} {_unary(rng)}",
                                f"Resources.%{vn}[0] {_unary(rng)}",
                                f"Resources.*.{rng.choice(KEYS)} IN %{vn}",
                            ]
                        )
                    )
                elif kind < 0.75:
                    body.append(f"%{vn} {_op(rng)} {rng.choice(NUMS)}")
                else:
                    body.append(f"{rng.choice(['some ', ''])}%{vn} {_op(rng)} {_lit(rng)}")
            else:
                body.append(_clause(rng, ci))
        joiner = " or\n    " if rng.random() < 0.25 else "\n    "
        parts.append(
            f"rule t{ti}_r{ri}{gate} {{\n    " + joiner.join(body) + "\n}"
        )
    return "\n\n".join(parts)


def _oracle(rf, doc):
    from guard_tpu.commands.report import rule_statuses_from_root

    scope = RootScope(rf, doc)
    try:
        eval_rules_file(rf, scope, None)
    except GuardError:
        return None
    root = scope.reset_recorder().extract()
    return {n: s.value for n, s in rule_statuses_from_root(root).items()}


TRIALS = 30


@pytest.mark.parametrize("seed", [11, 222, 3333])
def test_kernel_differential_fuzz(seed):
    rng = random.Random(seed)
    checked = 0
    for ti in range(TRIALS):
        rules_text = _rand_rules(rng, ti)
        try:
            rf = parse_rules_file(rules_text, "fuzz.guard")
        except GuardError:
            continue  # generator produced an unparseable combination
        docs_plain = [_rand_doc(rng) for _ in range(6)]
        docs = [from_plain(d) for d in docs_plain]
        fn_vars, fn_vals, fn_err = precompute_fn_values(rf, docs)
        batch, interner = encode_batch(
            docs, fn_values=fn_vals, fn_var_order=fn_vars
        )
        compiled = compile_rules_file(rf, interner)
        if not compiled.rules:
            continue
        evaluator = BatchEvaluator(compiled)
        statuses = evaluator(batch)
        unsure = evaluator.last_unsure
        for di in range(len(docs)):
            if di in fn_err:
                continue  # routed to the oracle (error path) by design
            oracle = _oracle(rf, docs[di])
            if oracle is None:
                assert unsure is not None and bool(unsure[di].any()), (
                    f"seed={seed} trial={ti} doc={di}: oracle raises but "
                    f"no unsure flag\n{rules_text}\n{docs_plain[di]}"
                )
                continue
            for ri, crule in enumerate(compiled.rules):
                if unsure is not None and bool(unsure[di, ri]):
                    continue
                dev = STATUS[int(statuses[di, ri])]
                assert dev == oracle[crule.name], (
                    f"seed={seed} trial={ti} doc={di} rule={crule.name}: "
                    f"device={dev} oracle={oracle[crule.name]}\n"
                    f"RULES:\n{rules_text}\nDOC: {docs_plain[di]}"
                )
                checked += 1
    assert checked > 100, f"fuzz exercised too little: {checked}"
