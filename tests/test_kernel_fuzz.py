"""Seeded randomized differential fuzz of the DEVICE lowering — the CI
smoke tier of tools/kernel_fuzz.py (the nightly tier runs the same
generator for a 420 s budget plus corpus-seeded trials). Random rule
files over random documents; every (doc, rule) status compared between
the compiled kernels and the CPU oracle. Deterministic seeds keep CI
stable; the tagged grammar lets the test assert the generator really
exercises every lowered construct family."""

import pathlib
import random
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))

import kernel_fuzz  # noqa: E402

TRIALS = 30


@pytest.mark.parametrize("seed", [11, 222, 3333])
def test_kernel_differential_fuzz(seed):
    rng = random.Random(seed)
    tags = set()
    checked = 0
    divergences = []
    for ti in range(TRIALS):
        c, div = kernel_fuzz.run_trial(rng, ti, tags)
        checked += c
        divergences.extend(div)
    assert not divergences, f"seed={seed}:\n" + "\n---\n".join(divergences[:3])
    assert checked > 100, f"fuzz exercised too little: {checked}"


def test_generator_covers_every_tagged_construct():
    """Across a fixed seed set the generator must emit every construct
    family the kernels lower (ALL_TAGS) — a shrunken grammar would
    silently stop testing shapes."""
    tags = set()
    for seed in range(24):
        rng = random.Random(seed)
        for ti in range(12):
            kernel_fuzz.rand_rules(rng, ti, tags)
        if kernel_fuzz.ALL_TAGS <= tags:
            break
    missing = kernel_fuzz.ALL_TAGS - tags
    assert not missing, sorted(missing)


def test_corpus_seeded_trial_runs():
    rng = random.Random(7)
    corpus = sorted((REPO / "corpus" / "rules").glob("*.guard"))
    assert corpus
    checked, div = kernel_fuzz.run_corpus_trial(rng, corpus[0])
    assert not div, div[:2]
