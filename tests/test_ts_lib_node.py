"""The npm package ships a GENERATED CommonJS build (ts_lib/dist/,
produced by tools/ts_build.py) the way the reference ships generated
wasm glue. The drift gate regenerates the build from the TypeScript
source and fails on any difference — the build is never hand-edited.
When node is present the smoke test EXECUTES the build end to end
against the real engine, including the persistent `serve --stdio`
session."""

import pathlib
import shutil
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent


def test_dist_is_generated_and_current():
    """`python tools/ts_build.py --check` — committed dist must equal
    the generated output byte for byte."""
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "ts_build.py"), "--check"],
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_generated_js_has_no_typescript_residue():
    import re

    js = (REPO / "ts_lib" / "dist" / "index.js").read_text()
    for pat in (
        r"\binterface\b",
        r"^import ",
        r"\bas\s+[A-Z]",
        r"as const",
        r"\?\s*:\s*\w+\s*[,)]",
        r":\s*Promise<",
    ):
        assert not re.search(pat, js, re.M), pat
    for name in ("validate", "createSession", "EXIT_CODES"):
        assert f"exports.{name} = {name};" in js


@pytest.mark.skipif(shutil.which("node") is None, reason="node unavailable")
def test_smoke_under_node():
    proc = subprocess.run(
        ["node", str(REPO / "ts_lib" / "smoke.js")],
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "ts_lib smoke OK" in proc.stdout
    assert "session smoke OK" in proc.stdout
