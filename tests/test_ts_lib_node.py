"""The npm package ships a runnable CommonJS build (ts_lib/dist/) the
way the reference ships its generated wasm glue. When node is present
these tests EXECUTE it end to end against the real engine; without
node they assert the hand-maintained build stays in sync with the
TypeScript source."""

import pathlib
import re
import shutil
import subprocess

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
TS = (REPO / "ts_lib" / "index.ts").read_text()
JS = (REPO / "ts_lib" / "dist" / "index.js").read_text()


def test_dist_build_in_sync_with_ts_source():
    # the CLI argument contract and exit-code protocol must match
    for token in [
        '"validate"', '"--structured"', '"-S", "none"', '"-o", "sarif"',
        "validationFailure: 19", "maxBuffer: 64 * 1024 * 1024",
    ]:
        assert token in TS and token in JS, token
    # every extension the TS walks, the JS walks
    for ext in re.findall(r'"\.(\w+)"', TS.split("const DATA_EXTENSIONS")[1].split(";")[0]):
        assert f'".{ext}"' in JS
    assert "exports.validate" in JS
    assert (REPO / "ts_lib" / "dist" / "index.d.ts").exists()


@pytest.mark.skipif(shutil.which("node") is None, reason="node unavailable")
def test_smoke_under_node():
    proc = subprocess.run(
        ["node", str(REPO / "ts_lib" / "smoke.js")],
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "ts_lib smoke OK" in proc.stdout
