"""Functional pins (reference guard/tests/functional.rs:7-80 analogue):
the full verbose JSON event tree for one validate call is pinned, and
the grammar parses every .guard file shipped with the reference
(pr.yml:168-200's registry parse check, run over the in-repo corpus)."""

import json
import pathlib

import pytest

from guard_tpu.cli import run
from guard_tpu.utils.io import Reader, Writer

REF = pathlib.Path("/root/reference")
GOLDEN = pathlib.Path(__file__).parent / "golden" / "event_tree.json"

needs_reference = pytest.mark.skipif(
    not REF.exists(), reason="reference checkout not available"
)


def _event_tree(args):
    w = Writer.buffered()
    code = run(args, writer=w)
    out = w.stripped()
    start = out.index("\n{")
    return code, json.loads(out[start:])


@needs_reference
def test_verbose_event_tree_pinned():
    rules = REF / "guard/resources/validate/rules-dir/s3_bucket_public_read_prohibited.guard"
    data = REF / "guard/resources/validate/data-dir/s3-public-read-prohibited-template-non-compliant.yaml"
    code, tree = _event_tree(
        ["validate", "-r", str(rules), "-d", str(data), "--print-json"]
    )
    assert code == 19
    expected = json.loads(GOLDEN.read_text())
    assert tree == expected


@needs_reference
def test_grammar_parses_every_reference_guard_file():
    from guard_tpu.core.errors import ParseError
    from guard_tpu.core.parser import parse_rules_file

    parsed = 0
    for root in ("guard-examples", "guard/resources", "docs"):
        for g in sorted((REF / root).rglob("*.guard")):
            text = g.read_text()
            if g.name.startswith("invalid_"):
                with pytest.raises(ParseError):
                    parse_rules_file(text, g.name)
                continue
            parse_rules_file(text, g.name)  # must not raise
            parsed += 1
    assert parsed >= 40


@needs_reference
def test_rulegen_matches_reference_golden():
    """rulegen output is byte-identical to the reference's golden file
    (guard/tests/rulegen.rs + resources/rulegen/output-dir)."""
    w = Writer.buffered()
    code = run(
        ["rulegen", "-t", str(
            REF / "guard/resources/rulegen/data-dir/"
            "s3-public-read-prohibited-template-compliant.yaml"
        )],
        writer=w,
    )
    assert code == 0
    golden = (
        REF / "guard/resources/rulegen/output-dir/test_rulegen_from_template.out"
    ).read_text()
    assert w.stripped() == golden


@needs_reference
def test_print_json_matches_reference_functional_golden():
    """Reproduces guard/tests/functional.rs:7-80: run_checks(verbose)
    must emit the reference's serde EventRecord encoding, compared
    against the reference's own expected JSON extracted from the test
    source (the reference test compares parsed values the same way)."""
    import re

    from guard_tpu.api import run_checks

    src = (REF / "guard/tests/functional.rs").read_text()
    expected = json.loads(
        re.search(r'let expected = r#"(.*?)"#;', src, re.S).group(1)
    )
    data = re.search(
        r'let data = String::from\(\s*r#"(.*?)"#,?\s*\)', src, re.S
    ).group(1)
    rule = 'AWS::ApiGateway::Method { Properties.AuthorizationType == "NONE"}'
    out = run_checks(
        data,
        rule,
        verbose=True,
        data_file_name="functional_test.json",
        rules_file_name="functional_test.rule",
    )
    assert json.loads(out) == expected
