"""Third batch of semantic cases ported from the reference's pinned
evaluation suite (guard/src/rules/eval_tests.rs)."""

import pytest

from guard_tpu.core.parser import parse_rules_file
from guard_tpu.core.scopes import RootScope
from guard_tpu.core.values import from_plain


def _status(rules, doc, rule=None):
    from guard_tpu.core.evaluator import eval_rules_file

    rf = parse_rules_file(rules, "t.guard")
    scope = RootScope(rf, from_plain(doc))
    if rule is None:
        return eval_rules_file(rf, scope, None).value
    return scope.rule_status(rule).value


def _clause_status(clause, doc):
    return _status(f"rule t {{ {clause} }}", doc, "t")


IAM_STATEMENTS = {
    "Statement": [
        {
            "Sid": "PrincipalPutObjectIfIpAddress",
            "Effect": "Allow",
            "Action": "s3:PutObject",
            "Resource": "arn:aws:s3:::my-service-bucket/*",
            "Condition": {
                "Bool": {"aws:ViaAWSService": "false"},
                "StringEquals": {"aws:SourceVpc": "vpc-12243sc"},
            },
        },
        {
            "Sid": "ServicePutObject",
            "Effect": "Allow",
            "Action": "s3:PutObject",
            "Resource": "arn:aws:s3:::my-service-bucket/*",
            "Condition": {"Bool": {"aws:ViaAWSService": "true"}},
        },
    ]
}

SOURCE_VPC_CLAUSE = (
    "SOME Statement[*].Condition.*[ THIS IS_STRUCT ]"
    "[ KEYS ==  /aws:[sS]ource(Vpc|VPC|Vpce|VPCE)/ ] NOT EMPTY"
)


def test_iam_statement_condition_key_filters():
    """eval_tests.rs test_iam_statement_clauses: chained filters over
    statement conditions, keys-filters after this-is-struct filters,
    upper-case operator forms."""
    clause = (
        "Statement[\n        Condition EXISTS ].Condition.*[\n"
        "            this is_struct ][ KEYS == /aws:[sS]ource(Vpc|VPC|Vpce|VPCE)/ ] NOT EMPTY"
    )
    assert _clause_status(clause, IAM_STATEMENTS) == "PASS"

    clause = (
        "Statement[ Condition EXISTS\n"
        "           Condition.*[ KEYS == /aws:[sS]ource(Vpc|VPC|Vpce|VPCE)/ ] !EMPTY ] NOT EMPTY"
    )
    assert _clause_status(clause, IAM_STATEMENTS) == "PASS"

    assert _clause_status(SOURCE_VPC_CLAUSE, IAM_STATEMENTS) == "PASS"


@pytest.mark.parametrize(
    "doc,expected",
    [
        (
            {"Statement": [{"Sid": "x", "Effect": "Allow", "Action": "s3:PutObject"}]},
            "FAIL",
        ),
        (
            {
                "Statement": [
                    {
                        "Sid": "x",
                        "Effect": "Allow",
                        "Action": "s3:PutObject",
                        "Condition": {"array": [1, 3, 4]},
                    }
                ]
            },
            "FAIL",
        ),
        (
            {
                "Statement": [
                    {
                        "Sid": "x",
                        "Effect": "Allow",
                        "Action": "s3:PutObject",
                        "Condition": {
                            "array": [1, 3, 4],
                            "StringEquals": {"aws:SourceVpc": "vpc-12243sc"},
                        },
                    }
                ]
            },
            "PASS",
        ),
    ],
)
def test_iam_statement_negative_and_mixed_cases(doc, expected):
    """eval_tests.rs test_iam_statement_clauses continued: missing
    conditions FAIL; non-struct condition values are filtered out by
    `this is_struct`; mixed structs still PASS."""
    assert _clause_status(SOURCE_VPC_CLAUSE, doc) == expected


def test_nested_tags_block_missing_fails():
    """eval_tests.rs rules_file_tests_simpler_correct_form...: nested
    Tags[*] block over a resource without Tags fails the whole file
    with a missing-block-value."""
    rules = """
rule iam_basic_checks {
    Resources[ Type == 'AWS::IAM::Role' ] {
        Properties {
            AssumeRolePolicyDocument.Version == /(\\d{4})-(\\d{2})-(\\d{2})/
            PermissionsBoundary == /arn:aws:iam::(\\d{12}):policy/
            Tags[*] {
                Key   == /[a-zA-Z0-9]+/
                Value == /[a-zA-Z0-9]+/
            }
        }
    }
}"""
    doc = {
        "Resources": {
            "iamrole": {
                "Type": "AWS::IAM::Role",
                "Properties": {
                    "PermissionsBoundary": "arn:aws:iam::123456789012:policy/permboundary",
                    "AssumeRolePolicyDocument": {"Version": "2021-01-10"},
                },
            },
            "iamRole2": {
                "Type": "AWS::IAM::Role",
                "Properties": {
                    "PermissionsBoundary": "arn:aws:iam::123456789112:policy/permboundary",
                    "AssumeRolePolicyDocument": {"Version": "2021-01-10"},
                    "Tags": [{"Key": "Key", "Value": "Value"}],
                },
            },
        }
    }
    assert _status(rules, doc) == "FAIL"
