"""The serving front door (guard_tpu/serve/frontdoor.py): per-tenant
admission quotas, the latency-SLO circuit breaker, overload shedding,
transport input bounds, and the two new traffic faces (POST /webhook,
sweep --follow) plus the Lambda front door.

Breaker and quota machines run on an INJECTED clock throughout — no
wall-time in any assertion, same discipline as the faults plane."""

import json
import socket
import threading
import time

import pytest

from guard_tpu.cli import run
from guard_tpu.commands.serve import Serve
from guard_tpu.serve import frontdoor
from guard_tpu.serve.frontdoor import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    AdmissionController,
    CircuitBreaker,
    QuotaExceeded,
)
from guard_tpu.utils.faults import POINTS, reset_faults
from guard_tpu.utils.io import Reader, Writer
from guard_tpu.utils.telemetry import ADMISSION_COUNTERS

RULES = "rule has_a { a exists }"


class Clock:
    """Deterministic monotonic clock for the front-door machines."""

    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now

    def advance(self, dt):
        self.now += dt


def _req(backend="cpu", doc='{"a": 1}', **extra):
    return json.dumps({
        "rules": [RULES], "data": [doc], "backend": backend, **extra,
    })


# -- circuit breaker state machine ---------------------------------------

def test_breaker_full_cycle_closed_open_half_open_closed():
    clk = Clock()
    br = CircuitBreaker(slo_s=0.05, cooldown_s=1.0, min_samples=4,
                        clock=clk)
    assert br.enabled
    assert br.state("d") == CLOSED
    assert br.decide("d") == "batch"
    # below the sample quorum a breach cannot trip
    for _ in range(3):
        br.observe("d", 0.2)
    assert br.state("d") == CLOSED
    br.observe("d", 0.2)  # quorum reached, p99 over SLO
    assert br.state("d") == OPEN
    assert br.decide("d") == "shed"
    # cooldown not yet elapsed: keep shedding
    clk.advance(0.5)
    assert br.decide("d") == "shed"
    # past cooldown: ONE probe rides the batcher, peers keep shedding
    clk.advance(0.6)
    assert br.decide("d") == "probe"
    assert br.state("d") == HALF_OPEN
    assert br.decide("d") == "shed"
    # probe meets the SLO: re-close, sample window cleared
    br.observe("d", 0.01, probe=True)
    assert br.state("d") == CLOSED
    assert br.decide("d") == "batch"
    # cleared window means a fresh quorum is needed to re-trip
    for _ in range(3):
        br.observe("d", 0.2)
    assert br.state("d") == CLOSED


def test_breaker_probe_miss_reopens():
    clk = Clock()
    br = CircuitBreaker(slo_s=0.05, cooldown_s=1.0, min_samples=2,
                        clock=clk)
    br.observe("d", 0.2)
    br.observe("d", 0.2)
    assert br.state("d") == OPEN
    clk.advance(1.0)
    assert br.decide("d") == "probe"
    br.observe("d", 0.2, probe=True)  # probe missed the SLO
    assert br.state("d") == OPEN
    assert br.decide("d") == "shed"
    clk.advance(1.0)
    assert br.decide("d") == "probe"  # cooldown grants another probe


def test_breaker_fast_samples_never_trip():
    clk = Clock()
    br = CircuitBreaker(slo_s=0.05, cooldown_s=1.0, min_samples=2,
                        clock=clk)
    for _ in range(20):
        br.observe("d", 0.001)
    assert br.state("d") == CLOSED
    assert br.decide("d") == "batch"


def test_breaker_queue_saturation_trips_immediately():
    clk = Clock()
    br = CircuitBreaker(slo_s=0.05, cooldown_s=1.0, min_samples=8,
                        clock=clk)
    b0 = ADMISSION_COUNTERS["breaker_trips"]
    br.on_queue_full("d")  # no sample quorum needed
    assert br.state("d") == OPEN
    assert br.decide("d") == "shed"
    assert ADMISSION_COUNTERS["breaker_trips"] - b0 == 1


def test_breaker_disabled_is_inert():
    clk = Clock()
    br = CircuitBreaker(slo_s=0.0, cooldown_s=1.0, min_samples=1,
                        clock=clk)
    assert not br.enabled
    for _ in range(10):
        br.observe("d", 99.0)
    br.on_queue_full("d")
    assert br.state("d") == CLOSED
    assert br.decide("d") == "batch"


def test_breaker_isolates_digests():
    clk = Clock()
    br = CircuitBreaker(slo_s=0.05, cooldown_s=1.0, min_samples=1,
                        clock=clk)
    br.observe("hot", 0.2)
    assert br.state("hot") == OPEN
    assert br.decide("hot") == "shed"
    # a different digest's machine is untouched
    assert br.state("cold") == CLOSED
    assert br.decide("cold") == "batch"


# -- admission controller -------------------------------------------------

def test_admission_rate_bucket_refills_on_clock():
    clk = Clock()
    ac = AdmissionController(rate=2.0, burst=2.0, max_inflight=0,
                             clock=clk)
    ac.admit("t")
    ac.admit("t")
    with pytest.raises(QuotaExceeded) as ei:
        ac.admit("t")
    assert ei.value.retry_after_ms == 500  # 1000 / rate
    # half a second refills exactly one token
    clk.advance(0.5)
    ac.admit("t")
    with pytest.raises(QuotaExceeded):
        ac.admit("t")


def test_admission_inflight_ceiling_and_release():
    clk = Clock()
    ac = AdmissionController(rate=0.0, burst=1.0, max_inflight=2,
                             clock=clk)
    ac.admit("t")
    ac.admit("t")
    with pytest.raises(QuotaExceeded) as ei:
        ac.admit("t")
    assert ei.value.retry_after_ms == 100
    ac.release("t")
    ac.admit("t")  # slot freed


def test_admission_buckets_are_per_tenant():
    clk = Clock()
    ac = AdmissionController(rate=1.0, burst=1.0, max_inflight=0,
                             clock=clk)
    ac.admit("hot")
    with pytest.raises(QuotaExceeded):
        ac.admit("hot")
    # the quiet tenant's bucket is its own
    ac.admit("quiet")


def test_admission_unlimited_is_inert():
    clk = Clock()
    ac = AdmissionController(rate=0.0, burst=1.0, max_inflight=0,
                             clock=clk)
    for _ in range(100):
        ac.admit("t")


# -- serve-level quota isolation (the satellite contract) -----------------

def test_serve_quota_rejection_envelope_and_quiet_parity():
    """A hot tenant over its bucket gets the structured 429-class
    envelope; a quiet tenant's envelope stays byte-identical to an
    unthrottled session."""
    clk = Clock()
    quiet_line = _req(tenant="quiet")
    hot_line = _req(tenant="hot")
    baseline = Serve(stdio=True).handle_line(quiet_line)
    assert baseline["code"] == 0

    srv = Serve(stdio=True)
    srv._get_frontdoor().admission = AdmissionController(
        rate=1.0, burst=1.0, max_inflight=0, clock=clk
    )
    r0 = ADMISSION_COUNTERS["rejected_rate"]
    assert srv.handle_line(hot_line)["code"] == 0
    for _ in range(3):  # hot tenant floods past its bucket
        rej = srv.handle_line(hot_line)
        assert rej["code"] == 5
        assert rej["error_class"] == "QuotaExceeded"
        assert rej["retry_after_ms"] == 1000
        assert "hot" in rej["error"]
    assert ADMISSION_COUNTERS["rejected_rate"] - r0 == 3
    # the quiet tenant rides through, envelope byte-identical
    assert srv.handle_line(quiet_line) == baseline
    # the hot tenant recovers once its bucket refills
    clk.advance(1.0)
    assert srv.handle_line(hot_line)["code"] == 0


# -- queue-full: shed vs structured 429 -----------------------------------

class _AlwaysFull:
    """Batcher stub whose admission queue never drains."""

    def __init__(self):
        self.calls = 0

    def submit(self, *a, **kw):
        self.calls += 1
        raise frontdoor.QueueFull("admission queue full (stub)",
                                  retry_after_ms=25)


def test_queue_full_sheds_to_solo_byte_identical(monkeypatch):
    line = _req(backend="tpu")
    solo = Serve(stdio=True, coalesce=False).handle_line(line)
    assert solo["code"] == 0

    srv = Serve(stdio=True, coalesce=True)
    srv._batcher = _AlwaysFull()
    s0 = ADMISSION_COUNTERS["shed_solo"]
    assert srv.handle_line(line) == solo
    assert ADMISSION_COUNTERS["shed_solo"] - s0 == 1


def test_queue_full_rejects_when_shed_disabled(monkeypatch):
    monkeypatch.setenv("GUARD_TPU_SERVE_SHED", "0")
    srv = Serve(stdio=True, coalesce=True)
    srv._batcher = _AlwaysFull()
    q0 = ADMISSION_COUNTERS["rejected_queue_full"]
    resp = srv.handle_line(_req(backend="tpu"))
    assert resp["code"] == 5
    assert resp["error_class"] == "QueueFull"
    assert resp["retry_after_ms"] == 25
    assert ADMISSION_COUNTERS["rejected_queue_full"] - q0 == 1


def test_queue_full_trips_breaker_and_opens_shed_route(monkeypatch):
    """With an SLO set, one saturated-queue event trips the breaker;
    the NEXT same-digest request routes straight to solo dispatch
    without ever touching the batcher."""
    monkeypatch.setenv("GUARD_TPU_SERVE_SLO_MS", "5000")
    monkeypatch.setenv("GUARD_TPU_BREAKER_COOLDOWN_MS", "3600000")
    line = _req(backend="tpu")
    solo = Serve(stdio=True, coalesce=False).handle_line(line)

    srv = Serve(stdio=True, coalesce=True)
    stub = srv._batcher = _AlwaysFull()
    b0 = ADMISSION_COUNTERS["breaker_trips"]
    assert srv.handle_line(line) == solo  # shed on the saturation
    assert ADMISSION_COUNTERS["breaker_trips"] - b0 == 1
    assert stub.calls == 1
    assert srv.handle_line(line) == solo  # breaker OPEN: pre-emptive shed
    assert stub.calls == 1  # batcher never consulted again


def test_serve_breaker_sheds_after_latency_trip(monkeypatch):
    """The real batcher path: a 1ns SLO means the first observed
    formation+dispatch latency trips the breaker, and the second
    request sheds — byte-identical to the sequential session."""
    monkeypatch.setenv("GUARD_TPU_COALESCE_WAIT_MS", "0")
    clk = Clock()
    line = _req(backend="tpu")
    solo = Serve(stdio=True, coalesce=False).handle_line(line)

    srv = Serve(stdio=True, coalesce=True)
    srv._get_frontdoor().breaker = CircuitBreaker(
        slo_s=1e-9, cooldown_s=3600.0, min_samples=1, clock=clk
    )
    assert srv.handle_line(line) == solo  # rides the batcher, trips
    from guard_tpu.ops.plan import plan_digest

    digest = plan_digest(srv._prepared_rules((RULES,)))
    assert srv._get_frontdoor().breaker.state(digest) == OPEN
    s0 = ADMISSION_COUNTERS["shed_solo"]
    assert srv.handle_line(line) == solo
    assert ADMISSION_COUNTERS["shed_solo"] - s0 == 1


def test_batcher_bounded_queue_wait_raises_queue_full():
    """CoalescingBatcher.submit(queue_wait=...) never wedges on a full
    admission queue: past the bounded wait it raises QueueFull for the
    front door to shed or 429."""
    from guard_tpu.serve.batcher import CoalescingBatcher

    ev = threading.Event()
    started = threading.Event()

    class _Slow:
        def execute(self, writer, reader):
            started.set()
            ev.wait(30)
            return 0

    b = CoalescingBatcher(wait_s=5.0, max_batch=8, queue_limit=1)
    try:
        t1 = threading.Thread(
            target=b.submit, args=(_Slow(), "{}", "d1", Writer.buffered())
        )
        t1.start()
        assert started.wait(10)  # dispatcher is now wedged in t1
        t2 = threading.Thread(
            target=b.submit, args=(_Slow(), "{}", "d2", Writer.buffered())
        )
        t2.start()
        deadline = time.monotonic() + 10
        while len(b._q) < 1 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert len(b._q) == 1  # queue at its limit, dispatcher busy
        with pytest.raises(frontdoor.QueueFull):
            b.submit(_Slow(), "{}", "d3", Writer.buffered(),
                     queue_wait=0.05)
        ev.set()
        t1.join(30)
        t2.join(30)
    finally:
        ev.set()
        b.close()


# -- transport input bounds ----------------------------------------------

def test_http_body_cap_answers_413(monkeypatch):
    from guard_tpu.serve.server import ServeServer
    import http.client

    monkeypatch.setenv("GUARD_TPU_SERVE_MAX_BODY", "200")
    srv = Serve(stdio=False)
    server = ServeServer(srv, "127.0.0.1:0").start()
    try:
        s0 = ADMISSION_COUNTERS["rejected_body_size"]
        conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                          timeout=30)
        conn.request("POST", "/validate", body="x" * 1000)
        resp = conn.getresponse()
        body = json.loads(resp.read())
        assert resp.status == 413
        assert body["error_class"] == "BodyTooLarge"
        assert ADMISSION_COUNTERS["rejected_body_size"] - s0 == 1
        conn.close()
        # an in-bounds request on a fresh connection still answers
        conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                          timeout=30)
        conn.request("POST", "/validate", body=_req())
        resp = conn.getresponse()
        assert resp.status == 200
        assert json.loads(resp.read())["code"] == 0
        conn.close()
    finally:
        server.stop()


def test_jsonl_line_cap_keeps_session_alive(monkeypatch):
    from guard_tpu.serve.server import ServeServer

    monkeypatch.setenv("GUARD_TPU_SERVE_MAX_BODY", "200")
    srv = Serve(stdio=False)
    server = ServeServer(srv, "127.0.0.1:0").start()
    try:
        with socket.create_connection(("127.0.0.1", server.port),
                                      timeout=30) as s:
            f = s.makefile("rwb")
            f.write((json.dumps({"junk": "y" * 500}) + "\n").encode())
            f.write((_req() + "\n").encode())
            f.flush()
            s.shutdown(socket.SHUT_WR)
            first = json.loads(f.readline())
            second = json.loads(f.readline())
        assert first["code"] == 5
        assert first["error_class"] == "BodyTooLarge"
        assert second["code"] == 0  # the oversized line did not end it
    finally:
        server.stop()


def test_http_quota_rejection_maps_to_429(monkeypatch):
    from guard_tpu.serve.server import ServeServer
    import http.client

    monkeypatch.setenv("GUARD_TPU_TENANT_RATE", "1")
    srv = Serve(stdio=False)
    server = ServeServer(srv, "127.0.0.1:0").start()
    try:
        def post():
            conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                              timeout=30)
            conn.request("POST", "/validate", body=_req())
            resp = conn.getresponse()
            out = (resp.status, resp.getheader("Retry-After"),
                   json.loads(resp.read()))
            conn.close()
            return out

        status, _, body = post()
        assert status == 200 and body["code"] == 0
        status, retry_after, body = post()  # bucket (burst 1) is empty
        assert status == 429
        assert body["error_class"] == "QuotaExceeded"
        assert int(retry_after) >= 1
    finally:
        server.stop()


# -- the webhook face -----------------------------------------------------

def _review(obj, uid="uid-1"):
    return json.dumps({
        "apiVersion": "admission.k8s.io/v1",
        "kind": "AdmissionReview",
        "request": {"uid": uid, "object": obj},
    })


@pytest.fixture
def webhook_serve(tmp_path):
    reg = tmp_path / "registry.guard"
    reg.write_text(RULES)
    return Serve(stdio=True, rules=[str(reg)])


def test_webhook_allows_compliant_object(webhook_serve):
    status, doc = webhook_serve.handle_webhook(_review({"a": 1}))
    assert status == 200
    r = doc["response"]
    assert r["uid"] == "uid-1"
    assert r["allowed"] is True
    assert r["status"]["code"] == 200
    assert doc["kind"] == "AdmissionReview"


def test_webhook_denies_with_rule_messages(webhook_serve):
    status, doc = webhook_serve.handle_webhook(
        _review({"b": 2}, uid="uid-2")
    )
    assert status == 200  # the HTTP exchange succeeded; the VERDICT denies
    r = doc["response"]
    assert r["uid"] == "uid-2"
    assert r["allowed"] is False
    assert r["status"]["code"] == 403
    assert "has_a" in r["status"]["message"].lower()


def test_webhook_malformed_review_is_422(webhook_serve):
    status, doc = webhook_serve.handle_webhook("{not json")
    assert status == 422
    assert doc["error_class"] == "ValueError"
    status, doc = webhook_serve.handle_webhook(json.dumps({"kind": "X"}))
    assert status == 422  # no `request` object


def test_webhook_without_registry_fails_open():
    status, doc = Serve(stdio=True).handle_webhook(_review({"b": 2}))
    assert status == 200
    assert doc["response"]["allowed"] is True
    assert "no rules configured" in doc["response"]["status"]["message"]


# -- streaming CI mode (sweep --follow) -----------------------------------

def _follow(tmp_path, lines, *extra):
    rules = tmp_path / "r.guard"
    rules.write_text(RULES)
    w = Writer.buffered()
    rc = run(
        ["sweep", "--follow", "-r", str(rules), "--backend", "cpu",
         *extra],
        writer=w,
        reader=Reader.from_string("\n".join(lines) + "\n"),
    )
    out = [json.loads(l) for l in w.out.getvalue().splitlines()
           if l.strip()]
    return rc, out[:-1], out[-1]


def test_follow_answers_every_line_in_order(tmp_path):
    rc, results, summary = _follow(tmp_path, [
        json.dumps({"name": "good", "content": '{"a": 1}'}),
        '{"a": 2}',            # a bare JSON document is its own content
        json.dumps({"name": "bad", "content": '{"b": 2}'}),
        "::not json::",        # quarantined, still answered in order
    ])
    assert [r["name"] for r in results] == [
        "good", "stream[2]", "bad", "stream[4]",
    ]
    assert results[0]["status"] == "pass" and results[0]["fails"] == []
    assert results[1]["status"] == "pass"
    assert results[2]["status"] == "fail" and results[2]["fails"]
    assert "quarantined" in results[3]
    assert summary["follow"] is True
    assert summary["documents"] == 4
    assert summary["counts"]["pass"] == 2
    assert summary["counts"]["fail"] == 1
    assert summary["errors"] == 1
    assert len(summary["quarantined"]) == 1
    assert rc == 19  # a failing doc is the sweep FAIL exit


def test_follow_clean_stream_exits_zero(tmp_path):
    rc, results, summary = _follow(tmp_path, ['{"a": 1}', '{"a": 2}'])
    assert rc == 0
    assert [r["status"] for r in results] == ["pass", "pass"]
    assert summary["counts"]["fail"] == 0
    assert "quarantined" not in summary


def test_follow_quarantine_budget_is_enforced(tmp_path):
    rc, results, summary = _follow(
        tmp_path, ['{"a": 1}', "::not json::"],
        "--max-doc-failures", "0",
    )
    assert rc == 5  # past the budget the stream exits ERROR
    assert summary["documents"] == 2


def test_follow_counters_ride_the_admission_group(tmp_path):
    d0 = ADMISSION_COUNTERS["follow_docs"]
    b0 = ADMISSION_COUNTERS["follow_batches"]
    _follow(tmp_path, ['{"a": 1}', '{"a": 2}', '{"a": 3}'])
    assert ADMISSION_COUNTERS["follow_docs"] - d0 == 3
    assert ADMISSION_COUNTERS["follow_batches"] - b0 >= 1


# -- the Lambda front door ------------------------------------------------

def test_lambda_legacy_event_shape_is_preserved(monkeypatch):
    from guard_tpu import lambda_handler

    monkeypatch.setattr(lambda_handler, "_SESSION", None)
    out = lambda_handler.handler({
        "data": '{"a": 1}', "rules": [RULES], "verbose": False,
    })
    assert set(out) == {"message"}
    assert len(out["message"]) == 1


def test_lambda_frontdoor_event_routes_through_serve(monkeypatch):
    from guard_tpu import lambda_handler

    monkeypatch.setattr(lambda_handler, "_SESSION", None)
    ok = lambda_handler.handler({
        "documents": [{"a": 1}], "rules": [RULES], "backend": "cpu",
    })
    assert ok["code"] == 0
    assert json.loads(ok["output"])["version"] == "2.1.0"
    fail = lambda_handler.handler({
        "documents": [{"b": 2}], "rules": [RULES], "backend": "cpu",
    })
    assert fail["code"] == 19


def test_lambda_frontdoor_quota_rejection_is_structured(monkeypatch):
    from guard_tpu import lambda_handler

    monkeypatch.setattr(lambda_handler, "_SESSION", None)
    clk = Clock()
    ev = {"documents": [{"a": 1}], "rules": [RULES], "backend": "cpu",
          "tenant": "burst-caller"}
    assert lambda_handler.handler(ev)["code"] == 0
    lambda_handler._SESSION._get_frontdoor().admission = (
        AdmissionController(rate=1.0, burst=1.0, max_inflight=0,
                            clock=clk)
    )
    assert lambda_handler.handler(ev)["code"] == 0  # first token
    rej = lambda_handler.handler(ev)
    assert rej["code"] == 5
    assert rej["error_class"] == "QuotaExceeded"
    assert rej["retry_after_ms"] == 1000
    monkeypatch.setattr(lambda_handler, "_SESSION", None)


# -- front-door fault points ----------------------------------------------

def test_front_door_fault_points_registered():
    assert "admission" in POINTS
    assert "shed" in POINTS


def test_injected_admission_fault_answers_structured(monkeypatch):
    monkeypatch.setenv("GUARD_TPU_FAULT", "admission:nth=1")
    reset_faults()
    try:
        srv = Serve(stdio=True)
        r1 = srv.handle_line(_req())
        assert r1["code"] == 5
        assert r1["error_class"] == "InjectedFault"
        r2 = srv.handle_line(_req())  # nth=1 fired once; session alive
        assert r2["code"] == 0
    finally:
        monkeypatch.delenv("GUARD_TPU_FAULT")
        reset_faults()


def test_injected_shed_fault_answers_structured(monkeypatch):
    monkeypatch.setenv("GUARD_TPU_FAULT", "shed:nth=1")
    reset_faults()
    try:
        srv = Serve(stdio=True, coalesce=True)
        srv._batcher = _AlwaysFull()  # force the shed path
        resp = srv.handle_line(_req(backend="tpu"))
        assert resp["code"] == 5
        assert resp["error_class"] == "InjectedFault"
    finally:
        monkeypatch.delenv("GUARD_TPU_FAULT")
        reset_faults()
