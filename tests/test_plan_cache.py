"""Compiled-plan artifact layer suite (guard_tpu/ops/plan.py): cache
key sensitivity (one rule byte, bucket shape, device fingerprint,
schema version each flip the digest; file names never do), bit-table
extension parity against direct lowering, the disk artifact round trip
(a warm cache performs zero lowering passes), corrupt/mismatched
artifacts degrading to misses with a warning, and the end-to-end
parity gates: plan-cached and --no-plan-cache runs must be
byte-identical across worker counts, pack modes, rule sharding and
every output format. The plan layer buys time, never bits."""

import json
import pickle

import numpy as np
import pytest

from guard_tpu.cli import run
from guard_tpu.commands.validate import RuleFile
from guard_tpu.core.parser import parse_rules_file
from guard_tpu.ops import plan as plan_mod
from guard_tpu.ops.encoder import Interner
from guard_tpu.ops.ir import compile_rules_file, extend_bit_tables
from guard_tpu.utils.io import Reader, Writer

RULES_A = (
    "let b = Resources.*[ Type == 'AWS::S3::Bucket' ]\n"
    "rule sse when %b !empty { %b.Properties.Enc == true }\n"
)
RULES_B = (
    "rule named { Resources.*.Properties.Name in ['web', 'db'] }\n"
    "rule arnish { Resources.*.Properties.Arn == /^arn:aws:/ }\n"
)
# count() makes the file function-variable: excluded from packing, it
# re-encodes + re-lowers per chunk on the plan's slow path
RULES_FN = (
    "let n = count(Resources.*)\n"
    "rule few { %n <= 4 }\n"
)


def _rule_file(content: str, name: str = "r.guard") -> RuleFile:
    return RuleFile(
        name=name, full_name=name, content=content,
        rules=parse_rules_file(content, name),
    )


@pytest.fixture(autouse=True)
def _fresh_plan_state(tmp_path, monkeypatch):
    """Each test gets an empty memo and its own artifact directory."""
    monkeypatch.setenv("GUARD_TPU_PLAN_CACHE_DIR", str(tmp_path / "plans"))
    plan_mod.clear_plan_memo()
    plan_mod.reset_plan_stats()
    yield
    plan_mod.clear_plan_memo()
    plan_mod.reset_plan_stats()


def _mk_corpus(tmp_path, n=8, fail=(2,), extra_rules=()):
    data = tmp_path / "data"
    data.mkdir(exist_ok=True)
    rule_paths = []
    for i, content in enumerate((RULES_A,) + tuple(extra_rules)):
        p = tmp_path / f"rules{i}.guard"
        p.write_text(content)
        rule_paths.append(str(p))
    for i in range(n):
        doc = {
            "Resources": {
                f"b{i}": {
                    "Type": "AWS::S3::Bucket",
                    "Properties": {
                        "Enc": i not in fail,
                        "Name": "web" if i % 2 else "worker",
                        "Arn": f"arn:aws:s3:::b{i}",
                    },
                }
            }
        }
        (data / f"t{i:02d}.json").write_text(json.dumps(doc))
    return rule_paths, data


# ------------------------------------------------------ cache key


def test_plan_key_changes_with_one_rule_byte():
    rf = _rule_file(RULES_A)
    tweaked = _rule_file(RULES_A.replace("true", "false"))
    assert plan_mod.plan_key([rf]) != plan_mod.plan_key([tweaked])
    # and is stable for byte-identical content in fresh objects
    assert plan_mod.plan_key([rf]) == plan_mod.plan_key(
        [_rule_file(RULES_A)]
    )


def test_plan_key_ignores_file_names():
    a = _rule_file(RULES_A, name="one.guard")
    b = _rule_file(RULES_A, name="two.guard")
    assert plan_mod.plan_key([a]) == plan_mod.plan_key([b])


def test_plan_key_covers_file_order():
    a, b = _rule_file(RULES_A), _rule_file(RULES_B)
    assert plan_mod.plan_key([a, b]) != plan_mod.plan_key([b, a])


def test_plan_key_sensitive_to_every_environment_axis():
    rf = _rule_file(RULES_A)
    base = plan_mod.plan_key(
        [rf], device_kind="cpu", device_count=8,
    )
    assert base != plan_mod.plan_key(
        [rf], device_kind="tpu", device_count=8,
    )
    assert base != plan_mod.plan_key(
        [rf], device_kind="cpu", device_count=4,
    )
    assert base != plan_mod.plan_key(
        [rf], device_kind="cpu", device_count=8,
        schema_version=plan_mod.PLAN_SCHEMA_VERSION + 1,
    )
    assert base != plan_mod.plan_key(
        [rf], device_kind="cpu", device_count=8, buckets=(64, 256),
    )
    assert base != plan_mod.plan_key(
        [rf], device_kind="cpu", device_count=8, pack_max_rules=7,
    )


# ------------------------------------------- extension vs direct lower


def test_extend_bit_tables_matches_direct_lowering():
    """A plan lowered against an EMPTY interner and then extended over
    the corpus strings must hold bit tables identical to IR lowered
    directly against an interner that already knew those strings."""
    rules = parse_rules_file(RULES_B, "r.guard")
    corpus = [
        "web", "db", "worker", "arn:aws:s3:::b1", "arn:gcp:thing", "",
    ]

    direct_int = Interner()
    for s in corpus:
        direct_int.intern(s)
    direct = compile_rules_file(rules, direct_int)

    plan_int = Interner()
    lazy = compile_rules_file(rules, plan_int)
    assert all(len(t) == 0 for t, _tg in lazy.bit_tables)
    for s in corpus:
        plan_int.intern(s)
    extend_bit_tables([lazy], plan_int)

    assert len(lazy.bit_tables) == len(direct.bit_tables)
    assert len(lazy.bit_specs) == len(lazy.bit_tables)
    for (lt, ltg), (dt, dtg) in zip(lazy.bit_tables, direct.bit_tables):
        assert ltg == dtg
        np.testing.assert_array_equal(lt, dt)
    np.testing.assert_array_equal(lazy.str_empty_bits,
                                  direct.str_empty_bits)


def test_extend_bit_tables_grows_shared_arrays_once():
    """pack_compiled aliases part tables by reference; the id()-memo
    must grow each underlying array exactly once and rebind every
    alias, keeping the pack and its parts in lockstep."""
    from guard_tpu.ops.ir import pack_compiled

    interner = Interner()
    a = compile_rules_file(parse_rules_file(RULES_A, "a"), interner)
    b = compile_rules_file(parse_rules_file(RULES_B, "b"), interner)
    packed = pack_compiled([a, b])
    for s in ("web", "db", "arn:aws:x", ""):
        interner.intern(s)
    extend_bit_tables([a, b, packed.compiled], interner)
    n = len(interner.strings)
    for comp in (a, b, packed.compiled):
        assert all(len(t) == n for t, _tg in comp.bit_tables)
        assert len(comp.str_empty_bits) == n
    # aliases stayed aliases: the pack's tables are the parts' tables
    # (a contributes none here), rebound to the same grown arrays —
    # never re-evaluated into diverging copies
    part_tables = [t for t, _tg in a.bit_tables + b.bit_tables]
    for pt, _tg in packed.compiled.bit_tables:
        assert any(pt is t for t in part_tables)
    # a second pass over an unchanged interner is a no-op
    assert extend_bit_tables([a, b, packed.compiled], interner) == 0


# ------------------------------------------------------ disk artifacts


def test_disk_roundtrip_skips_lowering(monkeypatch):
    rfs = [_rule_file(RULES_A), _rule_file(RULES_B)]
    plan_mod.get_plan(rfs)
    stats = plan_mod.plan_stats()
    assert stats["misses"] == 1 and stats["artifacts_saved"] == 1
    arts = list(plan_mod.plan_cache_dir().glob("*.plan"))
    assert len(arts) == 1

    # fresh "process": memo gone, artifact present — the build path
    # must never run again
    plan_mod.clear_plan_memo()
    plan_mod.reset_plan_stats()

    def _boom(_rfs):
        raise AssertionError("warm cache must not rebuild")

    monkeypatch.setattr(plan_mod, "build_plan", _boom)
    plan = plan_mod.get_plan([_rule_file(RULES_A), _rule_file(RULES_B)])
    stats = plan_mod.plan_stats()
    assert stats["hits"] == 1 and stats["misses"] == 0
    assert stats["bytes_loaded"] > 0
    # the loaded plan is canonical: empty interner, no corpus leakage
    assert len(plan.interner.strings) == 0
    assert all(
        len(t) == 0 for c in plan.all_compiled() for t, _tg in c.bit_tables
    )


def test_saved_artifact_stays_corpus_independent():
    """Relocation AFTER the save must not leak chunk strings into the
    on-disk artifact (it is written before first use)."""
    from guard_tpu.core.values import from_plain
    from guard_tpu.ops.encoder import encode_batch

    rfs = [_rule_file(RULES_B)]
    plan = plan_mod.get_plan(rfs)
    chunk = Interner()
    batch, _ = encode_batch(
        [from_plain({"Resources": {"x": {"Properties": {"Name": "web"}}}})],
        chunk,
    )
    plan_mod.relocate_batch(plan, batch, chunk)
    assert len(plan.interner.strings) > 0  # live plan grew
    reloaded = plan_mod.load_plan(plan.digest)
    assert reloaded is not None
    assert len(reloaded.interner.strings) == 0  # artifact did not


def test_corrupt_artifact_degrades_to_miss(caplog):
    rfs = [_rule_file(RULES_A)]
    plan_mod.get_plan(rfs)
    art = next(plan_mod.plan_cache_dir().glob("*.plan"))
    art.write_bytes(b"\x00garbage, not a pickle")
    plan_mod.clear_plan_memo()
    plan_mod.reset_plan_stats()
    with caplog.at_level("WARNING", logger="guard_tpu.plan"):
        plan = plan_mod.get_plan([_rule_file(RULES_A)])
    assert plan is not None
    stats = plan_mod.plan_stats()
    assert stats["misses"] == 1 and stats["hits"] == 0
    assert any("treating as a cache miss" in r.message for r in
               caplog.records)
    # the rebuild rewrote a valid artifact in place
    assert plan_mod.load_plan(plan.digest) is not None


@pytest.mark.parametrize("mutate", [
    lambda p: {**p, "schema": p["schema"] + 1},
    lambda p: {**p, "version": "0.0.0-other"},
    lambda p: {**p, "digest": "0" * 64},
    lambda p: ["not", "a", "dict"],
])
def test_mismatched_artifact_payloads_are_misses(mutate, caplog):
    rfs = [_rule_file(RULES_A)]
    plan = plan_mod.get_plan(rfs)
    art = plan_mod._artifact_path(plan.digest)
    payload = pickle.loads(art.read_bytes())
    art.write_bytes(pickle.dumps(mutate(payload)))
    with caplog.at_level("WARNING", logger="guard_tpu.plan"):
        assert plan_mod.load_plan(plan.digest) is None


def test_unwritable_cache_dir_warns_and_continues(monkeypatch, caplog,
                                                  tmp_path):
    blocker = tmp_path / "blocked"
    blocker.write_text("a file where the cache dir should be")
    monkeypatch.setenv("GUARD_TPU_PLAN_CACHE_DIR", str(blocker))
    with caplog.at_level("WARNING", logger="guard_tpu.plan"):
        plan = plan_mod.get_plan([_rule_file(RULES_A)])
    assert plan is not None  # persistence failure is never fatal
    assert plan_mod.plan_stats()["artifacts_saved"] == 0


# ------------------------------------------------------- parity gates


def _sweep(rule_paths, data, tmp_path, tag, *extra):
    w = Writer.buffered()
    rc = run(
        ["sweep", "-r", *rule_paths, "-d", str(data),
         "-M", str(tmp_path / f"m-{tag}.jsonl"), "-c", "4",
         "--backend", "tpu", *extra],
        writer=w, reader=Reader(),
    )
    summary = json.loads(w.out.getvalue())
    summary.pop("manifest", None)  # the only path-bearing key
    return rc, summary, w.err.getvalue()


@pytest.mark.parametrize("workers", [0, 2])
@pytest.mark.parametrize("pack", [(), ("--no-pack",)])
def test_sweep_parity_plan_vs_legacy(tmp_path, workers, pack):
    """Cold plan, warm plan and --no-plan-cache sweeps are identical
    in exit code, summary and stderr — per-file and packed, with and
    without ingest workers, fn-var slow path included."""
    rule_paths, data = _mk_corpus(
        tmp_path, n=8, fail=(2, 5), extra_rules=(RULES_B, RULES_FN)
    )
    common = ("--ingest-workers", str(workers), *pack)
    cold = _sweep(rule_paths, data, tmp_path, "cold", *common)
    assert plan_mod.plan_stats()["misses"] == 1
    warm = _sweep(rule_paths, data, tmp_path, "warm", *common)
    assert plan_mod.plan_stats()["hits"] >= 1
    legacy = _sweep(
        rule_paths, data, tmp_path, "off", *common, "--no-plan-cache"
    )
    assert cold == warm == legacy


def test_sweep_parity_rule_sharded(tmp_path):
    """Plan + PackShardedEvaluator: the per-shard pack memo re-extends
    cached packs after later chunks relocate, staying bit-identical to
    the legacy per-chunk repack."""
    rule_paths, data = _mk_corpus(
        tmp_path, n=8, fail=(1, 6), extra_rules=(RULES_B,)
    )
    on = _sweep(rule_paths, data, tmp_path, "on", "--rule-shards", "2")
    warm = _sweep(rule_paths, data, tmp_path, "w", "--rule-shards", "2")
    off = _sweep(
        rule_paths, data, tmp_path, "off", "--rule-shards", "2",
        "--no-plan-cache",
    )
    assert on == warm == off


def _validate(rule_paths, data, *extra):
    w = Writer.buffered()
    rc = run(
        ["validate", "-r", *rule_paths, "-d", str(data),
         "--backend", "tpu", *extra],
        writer=w, reader=Reader(),
    )
    return rc, w.out.getvalue(), w.err.getvalue()


@pytest.mark.parametrize(
    "fmt", ["single-line-summary", "json", "yaml", "junit", "sarif"]
)
def test_validate_output_modes_parity(tmp_path, fmt):
    rule_paths, data = _mk_corpus(
        tmp_path, n=6, fail=(1, 4), extra_rules=(RULES_B,)
    )
    extra = ("-o", fmt) + (
        ("--structured",) if fmt in ("json", "yaml", "junit", "sarif")
        else ()
    )
    cached = _validate(rule_paths, data, *extra)
    warm = _validate(rule_paths, data, *extra)
    legacy = _validate(rule_paths, data, *extra, "--no-plan-cache")
    assert cached == warm == legacy


def test_env_escape_hatch_disables_layer(tmp_path, monkeypatch):
    rule_paths, data = _mk_corpus(tmp_path, n=4, fail=(0,))
    monkeypatch.setenv("GUARD_TPU_PLAN_CACHE", "0")
    out = _sweep(rule_paths, data, tmp_path, "env-off")
    stats = plan_mod.plan_stats()
    assert stats["hits"] == stats["misses"] == 0
    assert not list(plan_mod.plan_cache_dir().glob("*.plan"))
    monkeypatch.delenv("GUARD_TPU_PLAN_CACHE")
    on = _sweep(rule_paths, data, tmp_path, "env-on")
    assert out == on
