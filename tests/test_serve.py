"""`guard-tpu serve --stdio`: the persistent validate session the npm
package drives (ts_lib createSession) — newline-delimited JSON
requests in, one JSON response line each, startup paid once."""

import json

from guard_tpu.cli import run
from guard_tpu.utils.io import Reader, Writer


def _serve(requests):
    w = Writer.buffered()
    rc = run(
        ["serve", "--stdio"],
        writer=w,
        reader=Reader.from_string("\n".join(requests) + "\n"),
    )
    lines = [l for l in w.out.getvalue().splitlines() if l.strip()]
    return rc, [json.loads(l) for l in lines]


def test_serve_pass_fail_and_error_codes():
    rc, resps = _serve([
        json.dumps({"rules": ["rule ok { a exists }"], "data": ['{"a": 1}']}),
        json.dumps({"rules": ["rule ok { a exists }"], "data": ['{"b": 1}']}),
        json.dumps({"rules": ["rule broken {{{"], "data": ['{"a": 1}']}),
    ])
    assert rc == 0
    assert [r["code"] for r in resps] == [0, 19, 5]
    sarif = json.loads(resps[0]["output"])
    assert sarif["version"] == "2.1.0"
    fail_sarif = json.loads(resps[1]["output"])
    assert any(
        "ok" in (res.get("ruleId") or "").lower()
        for run_ in fail_sarif["runs"]
        for res in run_["results"]
    )


def test_serve_malformed_request_keeps_session_alive():
    rc, resps = _serve([
        "this is not json",
        json.dumps({"rules": ["rule ok { a exists }"], "data": ['{"a": 1}']}),
    ])
    assert rc == 0
    assert resps[0]["code"] == 5
    assert resps[0]["error"]
    assert resps[1]["code"] == 0


def test_serve_output_formats():
    rc, resps = _serve([
        json.dumps({
            "rules": ["rule ok { a exists }"],
            "data": ['{"a": 1}'],
            "output_format": "json",
        }),
    ])
    assert rc == 0
    reports = json.loads(resps[0]["output"])
    assert reports[0]["status"] == "PASS"


def test_serve_empty_line_ends_session():
    w = Writer.buffered()
    rc = run(
        ["serve", "--stdio"],
        writer=w,
        reader=Reader.from_string(
            "\n"
            + json.dumps(
                {"rules": ["rule ok { a exists }"], "data": ['{"a": 1}']}
            )
            + "\n"
        ),
    )
    assert rc == 0
    assert w.out.getvalue().strip() == ""


def test_serve_reuses_prepared_rules_across_requests(monkeypatch):
    """Persistent sessions reuse the prepared pipeline: the second
    request with the same rules is served from the parsed-RuleFile
    cache (no re-parse), with byte-identical output — and a rules
    payload that fails to parse always takes the uncached path so the
    parse-error output reproduces every time."""
    import guard_tpu.commands.serve as serve_mod
    from guard_tpu.commands.serve import Serve
    from guard_tpu.utils.io import Reader, Writer

    calls = [0]
    real_parse = serve_mod.parse_rules_file

    def counting_parse(content, name):
        calls[0] += 1
        return real_parse(content, name)

    monkeypatch.setattr(serve_mod, "parse_rules_file", counting_parse)

    rules = ["rule ok { a exists }", "rule sized { a <= 3 }"]
    req = json.dumps({"rules": rules, "data": ['{"a": 1}']})
    req2 = json.dumps({"rules": rules, "data": ['{"a": 9}']})
    bad = json.dumps({"rules": ["rule broken {{{"], "data": ['{"a": 1}']})
    srv = Serve(stdio=True)
    w = Writer.buffered()
    rc = srv.execute(
        w, Reader.from_string("\n".join([req, req2, req, bad, bad]) + "\n")
    )
    assert rc == 0
    resps = [json.loads(l) for l in w.out.getvalue().splitlines() if l.strip()]
    assert [r["code"] for r in resps] == [0, 19, 0, 5, 5]
    # 2 parses for the first request's two rule files; requests 2 and 3
    # hit the cache; the broken payload parses (and fails) both times
    # in serve plus once per request inside validate's payload path
    assert srv.cache_hits == 2
    assert calls[0] == 4  # 2 (first request) + 1 + 1 (broken, uncached)
    # identical requests produce identical bytes (cache is transparent)
    assert resps[0]["output"] == resps[2]["output"]
    assert resps[3] == resps[4]
