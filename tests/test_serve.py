"""`guard-tpu serve --stdio`: the persistent validate session the npm
package drives (ts_lib createSession) — newline-delimited JSON
requests in, one JSON response line each, startup paid once."""

import json

from guard_tpu.cli import run
from guard_tpu.utils.io import Reader, Writer


def _serve(requests):
    w = Writer.buffered()
    rc = run(
        ["serve", "--stdio"],
        writer=w,
        reader=Reader.from_string("\n".join(requests) + "\n"),
    )
    lines = [l for l in w.out.getvalue().splitlines() if l.strip()]
    return rc, [json.loads(l) for l in lines]


def test_serve_pass_fail_and_error_codes():
    rc, resps = _serve([
        json.dumps({"rules": ["rule ok { a exists }"], "data": ['{"a": 1}']}),
        json.dumps({"rules": ["rule ok { a exists }"], "data": ['{"b": 1}']}),
        json.dumps({"rules": ["rule broken {{{"], "data": ['{"a": 1}']}),
    ])
    assert rc == 0
    assert [r["code"] for r in resps] == [0, 19, 5]
    sarif = json.loads(resps[0]["output"])
    assert sarif["version"] == "2.1.0"
    fail_sarif = json.loads(resps[1]["output"])
    assert any(
        "ok" in (res.get("ruleId") or "").lower()
        for run_ in fail_sarif["runs"]
        for res in run_["results"]
    )


def test_serve_malformed_request_keeps_session_alive():
    rc, resps = _serve([
        "this is not json",
        json.dumps({"rules": ["rule ok { a exists }"], "data": ['{"a": 1}']}),
    ])
    assert rc == 0
    assert resps[0]["code"] == 5
    assert resps[0]["error"]
    assert resps[1]["code"] == 0


def test_serve_output_formats():
    rc, resps = _serve([
        json.dumps({
            "rules": ["rule ok { a exists }"],
            "data": ['{"a": 1}'],
            "output_format": "json",
        }),
    ])
    assert rc == 0
    reports = json.loads(resps[0]["output"])
    assert reports[0]["status"] == "PASS"


def test_serve_empty_line_ends_session():
    w = Writer.buffered()
    rc = run(
        ["serve", "--stdio"],
        writer=w,
        reader=Reader.from_string(
            "\n"
            + json.dumps(
                {"rules": ["rule ok { a exists }"], "data": ['{"a": 1}']}
            )
            + "\n"
        ),
    )
    assert rc == 0
    assert w.out.getvalue().strip() == ""
