"""Sixth ported-semantics batch from the reference's eval_tests.rs:
the realistic policy-document cases — map-keys filters over IAM
condition blocks (test_map_keys_function:2294,
test_iam_statement_clauses:3146 with SAMPLE:3120), API-gateway rules
in both block styles (test_api_gateway:3273,
test_api_gateway_cleaner_model:3336), security-group egress filters
(testing_sg_rules_pro_serve:3507), and empty-list access
(ensure_all_list_value_access_on_empty_fails:2350). Statuses are
pinned where the reference asserts them; print-only reference cases
pin the oracle outcome derived from the rule semantics. Every case
also runs the device differential where the rules lower."""

import pytest

from test_lowering_round2 import _differential, _oracle

from guard_tpu.core.parser import parse_rules_file
from guard_tpu.core.values import from_plain


def _statuses(rules_text, doc_plain):
    rf = parse_rules_file(rules_text, "p6.guard")
    return _oracle(rf, from_plain(doc_plain))


API_GW_DOC = {
    "Resources": {
        "apigatewayapi": {
            "Type": "AWS::ApiGateway::RestApi",
            "Properties": {
                "Policy": {
                    "Version": "2012-10-17",
                    "Statement": [
                        {
                            "Sid": "PrincipalPutObjectIfIpAddress",
                            "Effect": "Allow",
                            "Action": "s3:PutObject",
                            "Resource": "arn:aws:s3:::my-service-bucket/*",
                            "Condition": {
                                "Bool": {"aws:ViaAWSService": "false"},
                                "StringEquals": {"aws:SourceVpc": "vpc-12243sc"},
                            },
                        },
                        {
                            "Sid": "ServicePutObject",
                            "Effect": "Allow",
                            "Action": "s3:PutObject",
                            "Resource": "arn:aws:s3:::my-service-bucket/*",
                            "Condition": {"Bool": {"aws:ViaAWSService": "true"}},
                        },
                    ],
                },
                "EndpointConfiguration": ["PRIVATE"],
            },
        }
    }
}


# eval_tests.rs:2294 (test_map_keys_function)
MAP_KEYS_RULES = """
let api_gw = Resources[ Type == 'AWS::ApiGateway::RestApi' ]
rule check_rest_api_is_private_and_has_access {
    %api_gw {
      Properties.EndpointConfiguration == ["PRIVATE"]
      some Properties.Policy.Statement[*].Condition[ keys == /aws:[sS]ource(Vpc|VPC|Vpce|VPCE)/ ] !empty
    }
}
"""


def test_map_keys_function():
    fail_doc = {
        "Resources": {
            "apiGw": {
                "Type": "AWS::ApiGateway::RestApi",
                "Properties": {
                    "EndpointConfiguration": ["PRIVATE"],
                    "Policy": {
                        "Statement": [
                            {
                                "Action": "Allow",
                                "Resource": ["*", "aws:"],
                                "Condition": {"aws:IsSecure": True},
                            }
                        ]
                    },
                },
            }
        }
    }
    assert (
        _statuses(MAP_KEYS_RULES, fail_doc)[
            "check_rest_api_is_private_and_has_access"
        ]
        == "FAIL"
    )
    pass_doc = {
        "Resources": {
            "apiGw": {
                "Type": "AWS::ApiGateway::RestApi",
                "Properties": {
                    "EndpointConfiguration": ["PRIVATE"],
                    "Policy": {
                        "Statement": [
                            {
                                "Action": "Allow",
                                "Resource": ["*", "aws:"],
                                "Condition": {
                                    "aws:IsSecure": True,
                                    "aws:sourceVpc": ["vpc-1234"],
                                },
                            }
                        ]
                    },
                },
            }
        }
    }
    assert (
        _statuses(MAP_KEYS_RULES, pass_doc)[
            "check_rest_api_is_private_and_has_access"
        ]
        == "PASS"
    )
    _differential(MAP_KEYS_RULES, [fail_doc, pass_doc])


# eval_tests.rs:2350 (ensure_all_list_value_access_on_empty_fails)
@pytest.mark.parametrize(
    "clause",
    [
        "Tags[*].Key == /Name/",
        "some Tags[*].Key == /Name/",
        "Tags[*] { Key == /Name/ }",
        "some Tags[*] { Key == /Name/ }",
    ],
)
def test_all_list_value_access_on_empty_fails(clause):
    doc = {"Tags": []}
    rules = f"rule r {{ {clause} }}"
    assert _statuses(rules, doc)["r"] == "FAIL"
    _differential(rules, [doc])


# eval_tests.rs:3146 (test_iam_statement_clauses; SAMPLE at :3120)
IAM_SAMPLE = {
    "Statement": [
        {
            "Sid": "PrincipalPutObjectIfIpAddress",
            "Effect": "Allow",
            "Action": "s3:PutObject",
            "Resource": "arn:aws:s3:::my-service-bucket/*",
            "Condition": {
                "Bool": {"aws:ViaAWSService": "false"},
                "StringEquals": {"aws:SourceVpc": "vpc-12243sc"},
            },
        },
        {
            "Sid": "ServicePutObject",
            "Effect": "Allow",
            "Action": "s3:PutObject",
            "Resource": "arn:aws:s3:::my-service-bucket/*",
            "Condition": {"Bool": {"aws:ViaAWSService": "true"}},
        },
    ]
}

NO_CONDITION = {
    "Statement": [
        {
            "Sid": "PrincipalPutObjectIfIpAddress",
            "Effect": "Allow",
            "Action": "s3:PutObject",
        }
    ]
}

ARRAY_CONDITION = {
    "Statement": [
        {
            "Sid": "PrincipalPutObjectIfIpAddress",
            "Effect": "Allow",
            "Action": "s3:PutObject",
            "Condition": {"array": [1, 3, 4]},
        }
    ]
}

MIXED_CONDITION = {
    "Statement": [
        {
            "Sid": "PrincipalPutObjectIfIpAddress",
            "Effect": "Allow",
            "Action": "s3:PutObject",
            "Condition": {
                "array": [1, 3, 4],
                "StringEquals": {"aws:SourceVpc": "vpc-12243sc"},
            },
        }
    ]
}

# the ViaAWSService-only variant (reference SAMPLE): no source-vpc key
VIA_ONLY = {
    "Statement": [
        {
            "Sid": "PrincipalPutObjectIfIpAddress",
            "Effect": "Allow",
            "Action": "s3:PutObject",
            "Resource": "arn:aws:s3:::my-service-bucket/*",
            "Condition": {"Bool": {"aws:ViaAWSService": "false"}},
        },
        {
            "Sid": "ServicePutObject",
            "Effect": "Allow",
            "Action": "s3:PutObject",
            "Resource": "arn:aws:s3:::my-service-bucket/*",
            "Condition": {"Bool": {"aws:ViaAWSService": "true"}},
        },
    ]
}

CLAUSE_A = (
    "Statement[ Condition exists ].Condition.*[ this is_struct ]"
    "[ keys == /aws:[sS]ource(Vpc|VPC|Vpce|VPCE)/ ] not empty"
)
CLAUSE_B = (
    "Statement[ Condition exists\n"
    "           Condition.*[ keys == /aws:[sS]ource(Vpc|VPC|Vpce|VPCE)/ ]"
    " !empty ] not empty"
)
CLAUSE_C = (
    "some Statement[*].Condition.*[ this is_struct ]"
    "[ keys == /aws:[sS]ource(Vpc|VPC|Vpce|VPCE)/ ] not empty"
)


@pytest.mark.parametrize(
    "clause,doc,expected",
    [
        (CLAUSE_A, IAM_SAMPLE, "PASS"),
        (CLAUSE_B, IAM_SAMPLE, "PASS"),
        (CLAUSE_C, IAM_SAMPLE, "PASS"),
        (CLAUSE_C, NO_CONDITION, "FAIL"),
        (CLAUSE_C, ARRAY_CONDITION, "FAIL"),
        (CLAUSE_C, MIXED_CONDITION, "PASS"),
        (CLAUSE_B, VIA_ONLY, "FAIL"),
    ],
)
def test_iam_statement_clauses(clause, doc, expected):
    rules = f"rule r {{ {clause} }}"
    assert _statuses(rules, doc)["r"] == expected
    _differential(rules, [doc])


# eval_tests.rs:3273 (test_api_gateway)
def test_api_gateway():
    rules = """
rule check_rest_api_private {
  AWS::ApiGateway::RestApi {
    Properties.EndpointConfiguration == ["PRIVATE"]
    Properties.Policy.Statement[ Condition.*[ keys == /aws:[sS]ource(Vpc|VPC|Vpce|VPCE)/ ] !empty ] !empty
  }
}
"""
    assert _statuses(rules, API_GW_DOC)["check_rest_api_private"] == "PASS"
    _differential(rules, [API_GW_DOC])


# eval_tests.rs:3336 (test_api_gateway_cleaner_model)
def test_api_gateway_cleaner_model():
    rules = """
rule check_rest_api_private {
  AWS::ApiGateway::RestApi {
    Properties {
        EndpointConfiguration == ["PRIVATE"]
        some Policy.Statement[*] {
            Condition.*[ keys == /aws:[sS]ource(Vpc|VPC|Vpce|VPCE)/ ] not empty
        }
    }
  }
}
"""
    assert _statuses(rules, API_GW_DOC)["check_rest_api_private"] == "PASS"
    _differential(rules, [API_GW_DOC])
    fail_doc = {
        "Resources": {
            "apigatewayapi": {
                "Type": "AWS::ApiGateway::RestApi",
                "Properties": {
                    "Policy": {
                        "Version": "2012-10-17",
                        "Statement": [
                            {
                                "Sid": "PrincipalPutObjectIfIpAddress",
                                "Effect": "Allow",
                                "Action": "s3:PutObject",
                                "Resource": "arn:aws:s3:::my-service-bucket/*",
                                # duplicate-key YAML collapses to the
                                # LAST Bool entry, like the reference's
                                # JSON parse
                                "Condition": {
                                    "Bool": {"aws:SecureTransport": "true"}
                                },
                            },
                            {
                                "Sid": "ServicePutObject",
                                "Effect": "Allow",
                                "Action": "s3:PutObject",
                                "Resource": "arn:aws:s3:::my-service-bucket/*",
                                "Condition": {
                                    "Bool": {"aws:ViaAWSService": "true"}
                                },
                            },
                        ],
                    },
                    "EndpointConfiguration": ["PRIVATE"],
                },
            }
        }
    }
    assert _statuses(rules, fail_doc)["check_rest_api_private"] == "FAIL"


# eval_tests.rs:3507 (testing_sg_rules_pro_serve — print-only in the
# reference; statuses pinned from the rule semantics: an egress rule
# open to the world FAILs, a scoped or absent egress list PASSes
# because the filter resolves empty / the query UnResolves to SKIP)
SG_RULES = """
let sgs = Resources.*[ Type == "AWS::EC2::SecurityGroup" ]

rule deny_egress when %sgs not empty {
    %sgs.Properties.SecurityGroupEgress[ CidrIp   == "0.0.0.0/0" or
                                         CidrIpv6 == "::/0" ] empty
}
"""


def _sg_doc(egress):
    props = {
        "GroupDescription": "foo/Counter/Service/SecurityGroup",
        "VpcId": {"Ref": "Vpc8378EB38"},
    }
    if egress is not None:
        props["SecurityGroupEgress"] = egress
    return {
        "Resources": {
            "CounterServiceSecurityGroupF41A3908": {
                "Type": "AWS::EC2::SecurityGroup",
                "Properties": props,
                "Metadata": {"aws:cdk:path": "foo/.../Resource"},
            }
        }
    }


@pytest.mark.parametrize(
    "egress,expected",
    [
        ([{"CidrIp": "0.0.0.0/0", "Description": "d", "IpProtocol": "-1"}], "FAIL"),
        ([{"CidrIpv6": "::/0", "Description": "d", "IpProtocol": "-1"}], "FAIL"),
        ([{"CidrIp": "10.0.0.0/16", "Description": "", "IpProtocol": "-1"}], "PASS"),
        (None, "PASS"),
    ],
)
def test_sg_egress_rules(egress, expected):
    doc = _sg_doc(egress)
    assert _statuses(SG_RULES, doc)["deny_egress"] == expected
    _differential(SG_RULES, [doc])


# eval_tests.rs:1044 (test_guard_10_compatibility_and_diff): Guard-2.0
# ALL-by-default semantics vs explicit `some`
def test_guard_10_compatibility_and_diff():
    doc1 = {"Statement": [{"Principal": ["*", "s3:*"]}]}
    all_rule = "rule r { Statement.*.Principal == '*' }"
    some_rule = "rule r { some Statement.*.Principal == '*' }"
    assert _statuses(all_rule, doc1)["r"] == "FAIL"
    assert _statuses(some_rule, doc1)["r"] == "PASS"
    doc2 = {
        "Statement": [
            {"Principal": "aws"},
            {"Principal": ["*", "s3:*"]},
        ]
    }
    assert _statuses(some_rule, doc2)["r"] == "PASS"
    _differential(all_rule, [doc1, doc2])
    _differential(some_rule, [doc1, doc2])


# eval_tests.rs:1785 (test_multiple_valued_clause_reporting): the rule
# status pins; the per-record reporting assertions are covered by the
# verbose-tree / --print-json functional pins (tests/test_functional_pin.py)
def test_multiple_valued_clause_status():
    doc = {
        "Resources": {
            "second": {"Properties": {"Name": "FAILEDMatch"}},
            "first": {"Properties": {"Name": "MatchNAME"}},
            "matches": {"Properties": {"Name": "MatchNAME"}},
            "failed": {"Properties": {"Name": "FAILEDMatch"}},
        }
    }
    direct = "rule name_check { Resources.*.Properties.Name == /NAME/ }"
    assert _statuses(direct, doc)["name_check"] == "FAIL"
    via_var = (
        "let resources = Resources.*\n"
        "rule name_check { %resources.Properties.Name == /NAME/ }"
    )
    assert _statuses(via_var, doc)["name_check"] == "FAIL"
    _differential(direct, [doc])
    _differential(via_var, [doc])
