"""GitHub Action dispatch tests with a recording fake API — the
equivalent of the reference's jest suites
(`/root/reference/action/__tests__/main.test.ts`) over the three
dispatch modes of `main.ts:31-50`: analyze (code-scanning upload),
pull_request (review comments), and push (summary only)."""

import base64
import gzip
import importlib.util
import json
import pathlib
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
RES = pathlib.Path("/root/reference/guard/resources/validate")

spec = importlib.util.spec_from_file_location(
    "guard_action_main", REPO / "action" / "main.py"
)
action = importlib.util.module_from_spec(spec)
spec.loader.exec_module(action)

needs_reference = pytest.mark.skipif(
    not RES.exists(), reason="reference checkout not available"
)


class FakeApi:
    """Records every request; returns scripted responses."""

    def __init__(self, responses=None):
        self.calls = []
        self.responses = responses or {}

    def request(self, method, path, body=None):
        self.calls.append((method, path, body))
        for (m, frag), resp in self.responses.items():
            if m == method and frag in path:
                return resp
        return {}


@pytest.fixture
def env(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)  # SARIF file lands in cwd
    monkeypatch.setenv("GITHUB_REPOSITORY", "octo/repo")
    monkeypatch.setenv("GITHUB_SHA", "deadbeef")
    monkeypatch.setenv("GITHUB_REF", "refs/heads/main")
    monkeypatch.setenv(
        "GITHUB_STEP_SUMMARY", str(tmp_path / "summary.md")
    )
    monkeypatch.setenv(
        "INPUT_RULES",
        str(RES / "rules-dir" / "s3_bucket_public_read_prohibited.guard"),
    )
    monkeypatch.setenv(
        "INPUT_DATA",
        str(RES / "data-dir" /
            "s3-public-read-prohibited-template-non-compliant.yaml"),
    )
    monkeypatch.setenv("INPUT_TOKEN", "tok")
    for k in ("INPUT_ANALYZE", "INPUT_CREATE_REVIEW", "INPUT_PATH",
              "GITHUB_EVENT_PATH"):
        monkeypatch.delenv(k, raising=False)
    return tmp_path


def _violating_uri(tmp_path):
    sarif = json.loads((tmp_path / "guard-tpu.sarif").read_text())
    return sarif["runs"][0]["results"][0]["locations"][0][
        "physicalLocation"]["artifactLocation"]["uri"]


@needs_reference
def test_analyze_mode_uploads_code_scan(env, monkeypatch):
    monkeypatch.setenv("INPUT_ANALYZE", "true")
    monkeypatch.setenv("GITHUB_EVENT_NAME", "push")
    api = FakeApi()
    assert action.main(api=api) == 1
    (method, path, body), = api.calls
    assert method == "POST"
    assert path == "/repos/octo/repo/code-scanning/sarifs"
    assert body["commit_sha"] == "deadbeef"
    assert body["ref"] == "refs/heads/main"
    decoded = json.loads(gzip.decompress(base64.b64decode(body["sarif"])))
    assert decoded["runs"][0]["results"], "uploaded SARIF has the findings"


@needs_reference
def test_push_mode_writes_summary_without_api_calls(env, monkeypatch):
    monkeypatch.setenv("GITHUB_EVENT_NAME", "push")
    api = FakeApi()
    assert action.main(api=api) == 1
    assert api.calls == []
    summary = (env / "summary.md").read_text()
    assert "Validation Failures" in summary
    assert "S3_BUCKET_PUBLIC_READ_PROHIBITED" in summary


@needs_reference
def test_pull_request_mode_posts_review_comments(env, monkeypatch):
    monkeypatch.setenv("GITHUB_EVENT_NAME", "pull_request")
    monkeypatch.setenv("INPUT_CREATE_REVIEW", "true")
    event = env / "event.json"
    # the changed-file list must include the violating file for comments
    # to post; first run once in push mode to learn the URI
    monkeypatch.setenv("GITHUB_EVENT_NAME", "push")
    action.main(api=FakeApi())
    uri = _violating_uri(env)
    monkeypatch.setenv("GITHUB_EVENT_NAME", "pull_request")
    event.write_text(json.dumps(
        {"pull_request": {"number": 7, "head": {"sha": "abc123"}}}
    ))
    monkeypatch.setenv("GITHUB_EVENT_PATH", str(event))

    stale = {"id": 99, "body": None, "path": uri, "position": None}
    api = FakeApi(responses={
        ("GET", "/pulls/7/files"): [{"filename": uri}],
        ("GET", "/pulls/7/comments"): [stale],
    })
    assert action.main(api=api) == 1

    posts = [c for c in api.calls if c[0] == "POST"]
    assert posts, "review comments must be created"
    for method, path, body in posts:
        assert path == "/repos/octo/repo/pulls/7/reviews"
        assert body["commit_id"] == "abc123"
        assert body["event"] == "COMMENT"
        (comment,) = body["comments"]
        assert comment["path"] == uri
        assert comment["position"] >= 1
        assert comment["body"].strip()
    summary = (env / "summary.md").read_text()
    assert "Validation Failures" in summary


@needs_reference
def test_pull_request_mode_deletes_stale_duplicate_comments(env, monkeypatch):
    monkeypatch.setenv("GITHUB_EVENT_NAME", "push")
    action.main(api=FakeApi())
    uri = _violating_uri(env)
    sarif = json.loads((env / "guard-tpu.sarif").read_text())
    first = sarif["runs"][0]["results"][0]
    dup = {
        "id": 42,
        "body": first["message"]["text"],
        "path": uri,
        "position": first["locations"][0]["physicalLocation"]["region"]["startLine"],
    }
    event = env / "event.json"
    event.write_text(json.dumps(
        {"pull_request": {"number": 7, "head": {"sha": "abc123"}}}
    ))
    monkeypatch.setenv("GITHUB_EVENT_PATH", str(event))
    monkeypatch.setenv("GITHUB_EVENT_NAME", "pull_request")
    monkeypatch.setenv("INPUT_CREATE_REVIEW", "true")
    api = FakeApi(responses={
        ("GET", "/pulls/7/files"): [{"filename": uri}],
        ("GET", "/pulls/7/comments"): [dup],
    })
    assert action.main(api=api) == 1
    deletes = [c for c in api.calls if c[0] == "DELETE"]
    assert deletes == [("DELETE", "/repos/octo/repo/pulls/comments/42", None)]


@needs_reference
def test_pull_request_unrelated_files_pass(env, monkeypatch):
    """Violations outside the PR's changed files do not fail the job
    (handlePullRequestRun returns no rows)."""
    event = env / "event.json"
    event.write_text(json.dumps(
        {"pull_request": {"number": 7, "head": {"sha": "abc123"}}}
    ))
    monkeypatch.setenv("GITHUB_EVENT_PATH", str(event))
    monkeypatch.setenv("GITHUB_EVENT_NAME", "pull_request")
    api = FakeApi(responses={
        ("GET", "/pulls/7/files"): [{"filename": "unrelated.yaml"}],
    })
    assert action.main(api=api) == 0


@needs_reference
def test_compliant_data_passes(env, monkeypatch):
    monkeypatch.setenv(
        "INPUT_DATA",
        str(RES / "data-dir" /
            "s3-public-read-prohibited-template-compliant.yaml"),
    )
    monkeypatch.setenv("GITHUB_EVENT_NAME", "push")
    api = FakeApi()
    assert action.main(api=api) == 0
    assert api.calls == []
