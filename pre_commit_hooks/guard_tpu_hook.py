"""pre-commit hook entry point.

Equivalent of `/root/reference/pre_commit_hooks/cfn_guard.py`: exposes
the `validate` and `test` commands to pre-commit. Unlike the reference
(which downloads a pinned release binary per-OS), this framework is a
Python package, so the hook simply invokes the in-process CLI —
no network access, no binary management.
"""

from __future__ import annotations

import argparse
import sys
from typing import Optional, Sequence

UNKNOWN_OPERATION_MSG = (
    "Unknown operation. guard-tpu pre-commit-hook only supports validate "
    "and test commands."
)


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(prog="guard-tpu-hook", add_help=False)
    parser.add_argument("--operation", default="validate")
    args, rest = parser.parse_known_args(argv)
    if args.operation not in ("validate", "test"):
        print(UNKNOWN_OPERATION_MSG, file=sys.stderr)
        return 1
    from guard_tpu.cli import run

    return run([args.operation, *rest])


if __name__ == "__main__":
    sys.exit(main())
